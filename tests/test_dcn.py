"""DCN through CTRTrainer end-to-end: cross layers learn an explicit
feature interaction a linear/wide model cannot."""

import numpy as np

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DCN
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("a", "b")


def test_dcn_learns_cross_interaction(tmp_path):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64)
    model = DCN(slot_names=SLOTS, emb_dim=8, num_cross_layers=2,
                hidden=(32,))
    tr = CTRTrainer(model, feed, TableConfig(dim=8, learning_rate=0.2),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10,
                                         dense_learning_rate=3e-3))
    tr.init(seed=0)
    rng = np.random.default_rng(9)
    p = str(tmp_path / "part")
    with open(p, "w") as f:
        for _ in range(512):
            a, b = rng.integers(1, 60), rng.integers(1, 60)
            # Pure INTERACTION signal: label depends on the (a, b) pair's
            # parity product, not on either feature alone.
            label = int(((a % 2) == (b % 2)) == (rng.random() < 0.85))
            f.write(f"{label} a:{a} b:{b}\n")
    losses = []
    for _ in range(7):
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist([p])
        ds.load_into_memory()
        stats = tr.train_pass(ds)
        losses.append(stats["loss"])
    assert losses[-1] < losses[0]
    assert stats["auc"] > 0.62, stats["auc"]


def test_dcn_apply_matches_numpy_reference():
    """model.apply (cross-only variant, hidden=()) against an
    independently written numpy transcription of CrossNet v2:
    x_{l+1} = x0 * (x_l W + b) + x_l, then the head + wide + bias."""
    import jax
    import jax.numpy as jnp

    model = DCN(slot_names=SLOTS, emb_dim=4, num_cross_layers=2,
                hidden=())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    bs = 3
    emb = {s: jnp.asarray(rng.normal(size=(bs, 4)), jnp.float32)
           for s in SLOTS}
    w = {s: jnp.asarray(rng.normal(size=(bs,)), jnp.float32)
         for s in SLOTS}
    segs = {s: jnp.arange(bs, dtype=jnp.int32) for s in SLOTS}
    got = np.asarray(model.apply(params, emb, w, segs, batch_size=bs))

    # numpy reference
    x0 = np.concatenate([np.asarray(emb[s]) for s in SLOTS], axis=-1)
    x = x0.copy()
    for layer in params["cross"]:
        x = x0 * (x @ np.asarray(layer["w"])
                  + np.asarray(layer["b"])) + x
    head = np.asarray(params["head"]["w"])
    logits = (x @ head)[:, 0] + np.asarray(params["head"]["b"])[0]
    wide = sum(np.asarray(w[s]) for s in SLOTS)
    expect = logits + wide + float(params["bias"])
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)
