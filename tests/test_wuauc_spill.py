"""WuAuc bounded-memory spill (VERDICT r02 task 10): 1M records through a
tiny RAM threshold must (a) keep resident record memory bounded by the
threshold, (b) produce EXACTLY the same wuauc as the all-in-RAM path."""

import numpy as np

from paddlebox_tpu.metrics.auc import wuauc_compute
from paddlebox_tpu.metrics.registry import (BucketAucCalculator,
                                            MetricRegistry)


def _records(n, n_users, seed=0):
    rng = np.random.default_rng(seed)
    uids = rng.integers(1, n_users + 1, n).astype(np.uint64)
    # predictions correlated with labels so wuauc is meaningfully > 0.5
    labels = (rng.random(n) < 0.3).astype(np.float64)
    preds = np.clip(0.25 * labels + rng.random(n) * 0.7, 0, 1)
    return uids, preds, labels


def test_spill_matches_exact_1m_records():
    n = 1_000_000
    uids, preds, labels = _records(n, n_users=50_000)
    exact = wuauc_compute(uids, preds, labels)

    cal = BucketAucCalculator(num_buckets=1 << 12, spill_records=100_000)
    chunk = 37_000                      # non-divisor chunking
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        cal.add_uid_data(preds[lo:hi], labels[lo:hi], uids[lo:hi])
        # Bounded residency: RAM record count never exceeds threshold
        # plus one chunk (the spill triggers after the append).
        assert cal._uid_in_ram <= 100_000 + chunk
    assert cal._spill_dir is not None   # it actually spilled

    from paddlebox_tpu.metrics.auc import wuauc_accumulate
    ws = wt = 0.0
    users = 0
    for u, p, l in cal.uid_record_partitions():
        s, w, c = wuauc_accumulate(u, p, l)
        ws += s
        wt += w
        users += c
    got = ws / wt
    np.testing.assert_allclose(got, exact["wuauc"], rtol=0, atol=1e-12)
    assert users == exact["wuauc_users"]
    cal.reset()
    assert cal._spill_dir is None       # spill files cleaned up


def test_registry_wuauc_spill_path():
    reg = MetricRegistry()
    reg.init_metric("w", "wuauc", bucket_size=1 << 12)
    # Force a tiny threshold on the underlying calculator.
    reg._metrics["w"].calculator.spill_records = 1_000
    uids, preds, labels = _records(20_000, n_users=500, seed=3)
    for lo in range(0, 20_000, 1_500):
        hi = lo + 1_500
        reg.add_data("w", preds[lo:hi], labels[lo:hi],
                     uids=uids[lo:hi])
    out = reg.get_metric("w")
    exact = wuauc_compute(uids, preds, labels)
    np.testing.assert_allclose(out["wuauc"], exact["wuauc"], atol=1e-12)
    assert out["wuauc_users"] == exact["wuauc_users"]
