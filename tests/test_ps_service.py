"""PS service + SSD tier tests: localhost in-process cluster (role of the
reference's fake-cluster mechanism, test_dist_base.py:1041) exercising
sharded pull/push with server-side sparse optimizer parity, dense tables,
save/load, and the RAM/disk tier movement with delta correctness."""

import numpy as np
import pytest

from paddlebox_tpu.distributed.ps import start_local_cluster
from paddlebox_tpu.embedding.ssd_tier import DiskShards, TieredFeatureStore
from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.embedding.table import TableConfig


@pytest.fixture
def cluster():
    cfg = TableConfig(name="emb", dim=4, optimizer="adagrad",
                      learning_rate=0.1)
    servers, client = start_local_cluster(3, {"emb": cfg},
                                          dense={"w0": np.ones((4,))})
    yield servers, client, cfg
    client.stop_servers()
    client.close()
    for s in servers:
        s.stop()


def test_pull_sparse_sharded_and_stable(cluster):
    _, client, _ = cluster
    keys = np.arange(1, 31, dtype=np.uint64)
    out1 = client.pull_sparse("emb", keys)
    assert out1["emb"].shape == (30, 4)
    # repeated pull returns identical (initialization persisted server-side)
    out2 = client.pull_sparse("emb", keys)
    np.testing.assert_array_equal(out1["emb"], out2["emb"])
    # duplicate keys get the same row
    dup = client.pull_sparse("emb", np.asarray([5, 5, 7], np.uint64))
    np.testing.assert_array_equal(dup["emb"][0], dup["emb"][1])


def test_push_sparse_applies_optimizer_with_dup_merge(cluster):
    _, client, cfg = cluster
    keys = np.asarray([11, 12, 11], np.uint64)  # 11 pushed twice
    before = client.pull_sparse("emb", np.asarray([11, 12], np.uint64))
    g = np.ones((3, 4), np.float32)
    client.push_sparse("emb", keys, emb_grad=g,
                       w_grad=np.ones((3,), np.float32),
                       show=np.ones((3,), np.float32),
                       click=np.zeros((3,), np.float32))
    after = client.pull_sparse("emb", np.asarray([11, 12], np.uint64))
    # adagrad: delta = -lr * g / sqrt(g2sum + init_g2sum); key 11 saw
    # grad 2 (merged), key 12 saw grad 1 -> key 11 moved further
    d11 = np.abs(after["emb"][0] - before["emb"][0]).mean()
    d12 = np.abs(after["emb"][1] - before["emb"][1]).mean()
    assert d11 > d12 > 0
    # server-side reference apply for key 12 (single grad of 1.0)
    store = FeatureStore(cfg)
    rows = store.pull_for_pass(np.asarray([12], np.uint64))
    e, _ = store.opt.update_vector(before["emb"][1:2], rows["emb_state"],
                                   np.ones((1, 4), np.float32))
    np.testing.assert_allclose(after["emb"][1], np.asarray(e)[0], rtol=1e-5)


def test_pull_push_pass_bulk(cluster):
    _, client, _ = cluster
    keys = np.sort(np.unique(np.random.default_rng(0).integers(
        1, 10000, 200).astype(np.uint64)))
    rows = client.pull_pass("emb", keys)
    assert rows["emb"].shape == (keys.size, 4)
    rows["emb"][:] = 7.0
    client.push_pass("emb", keys, rows)
    back = client.pull_pass("emb", keys)
    np.testing.assert_allclose(back["emb"], 7.0)


def test_pull_pass_empty_keeps_schema(cluster):
    """Zero-key pass returns fully-shaped (0, ...) field arrays (the
    FeatureStore contract PassEngine builds against), not {}."""
    _, client, _ = cluster
    rows = client.pull_pass("emb", np.empty((0,), np.uint64))
    assert rows["emb"].shape == (0, 4)
    assert rows["emb_state"].shape[0] == 0
    assert rows["w"].shape == (0,)


def test_dense_table_and_save_load(cluster, tmp_path):
    servers, client, _ = cluster
    np.testing.assert_allclose(client.pull_dense("w0"), 1.0)
    client.push_dense("w0", np.full((4,), 0.5))  # sgd lr=1.0: 1 - 0.5
    np.testing.assert_allclose(client.pull_dense("w0"), 0.5)
    # save, perturb, load restores
    keys = np.asarray([1, 2, 3], np.uint64)
    vals = client.pull_sparse("emb", keys)
    client.save(str(tmp_path / "ckpt"))
    client.push_sparse("emb", keys, emb_grad=np.ones((3, 4), np.float32),
                       w_grad=np.ones((3,), np.float32))
    client.load(str(tmp_path / "ckpt"))
    restored = client.pull_sparse("emb", keys)
    np.testing.assert_allclose(restored["emb"], vals["emb"])
    assert sum(s["emb"] for s in client.stats()) >= 3


def test_shrink_evicts_cold(cluster):
    _, client, _ = cluster
    keys = np.arange(100, 120, dtype=np.uint64)
    client.pull_sparse("emb", keys)  # show=0 rows
    n = client.shrink(min_show=0.5)
    assert n >= 20


def test_concurrent_pushes_not_lost(cluster):
    """Two clients racing on the same key must not lose updates (the
    server serializes the pull→optimizer→push RMW per table)."""
    import threading
    from paddlebox_tpu.distributed.ps import PSClient
    servers, client, _ = cluster
    key = np.asarray([33], np.uint64)
    client.pull_sparse("emb", key)

    def push_many():
        c = PSClient([s.endpoint for s in servers])
        for _ in range(20):
            c.push_sparse("emb", key,
                          emb_grad=np.ones((1, 4), np.float32),
                          w_grad=np.zeros((1,), np.float32),
                          show=np.ones((1,), np.float32))
        c.close()

    ts = [threading.Thread(target=push_many) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    # show accumulates exactly once per push: 4 threads * 20 pushes
    owner = int(key[0]) % len(servers)
    store = servers[owner].tables["emb"]
    rows = store.pull_for_pass(key)
    np.testing.assert_allclose(rows["show"], 80.0)


def test_client_raises_on_dead_shard(cluster):
    servers, client, _ = cluster
    servers[1].stop()
    keys = np.arange(0, 12, dtype=np.uint64)  # covers all 3 shards
    with pytest.raises(Exception):
        client.pull_sparse("emb", keys)


# ---------------------------------------------------------------------------
# SSD tier
# ---------------------------------------------------------------------------

def test_disk_shards_roundtrip(tmp_path):
    ds = DiskShards(str(tmp_path), num_buckets=4)
    keys = np.asarray([3, 9, 17, 1025], np.uint64)
    vals = {"emb": np.arange(16, dtype=np.float32).reshape(4, 4)}
    ds.write(keys, vals)
    assert ds.num_features == 4
    # upsert overrides
    ds.write(keys[:1], {"emb": np.full((1, 4), 9.0, np.float32)})
    k, v = ds.take(np.asarray([3, 17, 777], np.uint64))
    np.testing.assert_array_equal(np.sort(k), [3, 17])
    row3 = v["emb"][np.searchsorted(k, 3)]
    np.testing.assert_allclose(row3, 9.0)
    assert ds.num_features == 2  # taken rows removed


def test_tiered_store_stages_and_evicts(tmp_path):
    cfg = TableConfig(name="t", dim=4)
    ts = TieredFeatureStore(cfg, str(tmp_path / "ssd"), max_ram_features=8)
    k1 = np.arange(0, 16, dtype=np.uint64)
    rows = ts.pull_for_pass(k1)
    rows["show"][:] = np.arange(16, dtype=np.float32)  # 0..7 are coldest
    ts.push_from_pass(k1, rows)
    assert ts.ram.num_features == 8
    assert ts.disk.num_features == 8
    assert ts.num_features == 16
    # cold keys went to disk; pulling them stages exact values back
    got = ts.pull_for_pass(np.arange(0, 4, dtype=np.uint64))
    np.testing.assert_allclose(got["show"], [0, 1, 2, 3])
    np.testing.assert_allclose(got["emb"], rows["emb"][:4], rtol=1e-6)
    assert ts.contains(np.arange(0, 16, dtype=np.uint64)).all()
    assert not ts.contains(np.asarray([999], np.uint64)).any()


def test_tiered_store_delta_covers_evicted_rows(tmp_path):
    cfg = TableConfig(name="t", dim=2)
    ts = TieredFeatureStore(cfg, str(tmp_path / "ssd"), max_ram_features=4)
    keys = np.arange(0, 4, dtype=np.uint64)
    rows = ts.pull_for_pass(keys)
    ts.push_from_pass(keys, rows)
    ts.save_base(str(tmp_path / "base"))
    # train keys 0..3, then push 4 hot keys -> 0..3 evicted (coldest)
    rows = ts.pull_for_pass(keys)
    rows["emb"][:] = 42.0
    ts.push_from_pass(keys, rows)
    k2 = np.arange(10, 14, dtype=np.uint64)
    rows2 = ts.pull_for_pass(k2)
    rows2["show"][:] = 100.0
    ts.push_from_pass(k2, rows2)
    assert not ts.ram.contains(keys).any()  # original keys now on disk
    ts.save_delta(str(tmp_path / "delta"))
    # restore base+delta into a fresh store: trained values must survive
    fresh = TieredFeatureStore(cfg, str(tmp_path / "ssd2"))
    fresh.load(str(tmp_path / "base"), "base")
    fresh.load(str(tmp_path / "delta"), "delta")
    got = fresh.pull_for_pass(keys)
    np.testing.assert_allclose(got["emb"], 42.0)


def test_tiered_store_shrink_decays_disk(tmp_path):
    cfg = TableConfig(name="t", dim=2)
    ts = TieredFeatureStore(cfg, str(tmp_path / "ssd"), max_ram_features=2)
    keys = np.arange(0, 6, dtype=np.uint64)
    rows = ts.pull_for_pass(keys)
    rows["show"][:] = 1.0
    ts.push_from_pass(keys, rows)  # 4 rows spill to disk
    assert ts.disk.num_features == 4
    evicted = ts.shrink(min_show=0.99)  # decay pushes show below 0.99
    assert evicted == 6
    assert ts.num_features == 0


def test_tiered_rmw_preserves_disjoint_tiers(tmp_path):
    """The review repro: pull-then-push RMW on keys the pull's budget
    eviction spilled back to disk must NOT leave them in both tiers
    (duplicate export keys, stale disk values, inflated counts)."""
    cfg = TableConfig(name="emb", dim=4, optimizer="adagrad",
                      learning_rate=0.1)
    store = TieredFeatureStore(cfg, str(tmp_path), max_ram_features=2)
    seed = np.arange(1, 5, dtype=np.uint64)
    store.push_from_pass(seed, store.pull_for_pass(seed))
    cold = store.rows_by_coldness()[:2] if hasattr(
        store, "rows_by_coldness") else seed[:2]
    keys = np.sort(np.asarray(cold, np.uint64))
    vals = store.pull_for_pass(keys)          # may stage in + evict
    vals["emb"] = vals["emb"] + 1.0
    store.push_from_pass(keys, vals)          # RMW write-back
    assert store.num_features == 4
    out = store.save_xbox(str(tmp_path / "x"))
    assert out == 4
    from paddlebox_tpu.serving import load_xbox_model
    k, e, w = load_xbox_model(str(tmp_path / "x"), table="emb")
    assert np.array_equal(k, seed)            # unique, complete
    # The updated values won (not a stale disk copy).
    back = store.pull_for_pass(keys)
    np.testing.assert_allclose(back["emb"], vals["emb"], atol=1e-6)
