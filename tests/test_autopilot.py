"""Fleet autopilot unit/contract suite (ISSUE 20).

Pins the control-loop contracts AUTOPILOT.md documents:

- the trace generator is replay-pure — one config yields a
  byte-identical request sequence and bit-identical replica routing,
  the diurnal/spike rate shape and the hot-set skew are exactly as
  configured, and the replay driver fires chaos handlers on the virtual
  timeline;
- the autoscaler is hysteresis-guarded (a flap storm produces at most
  one scale action per cooldown window), clamps to
  FLAGS_autopilot_{min,max}_replicas, heals a below-floor fleet,
  drains the least-loaded replica on scale-in, repairs the shard tier
  on replication lag under its own cooldown, and a controller killed
  inside the journaled action window resumes without double-applying;
- the canary controller stages a new donefile base on a bounded subset,
  confines it there, promotes on clean COPC, rolls back (restoring the
  incumbent base, bumping ``serving/hotswap_rollbacks``) on a
  calibration breach, emits one ``autopilot_report {json}`` verdict
  line per resolution, and re-drives a journaled half-finished
  promote/rollback idempotently after a crash;
- the fleet publishes ``fleet/topology_epoch`` + per-replica state
  gauges into attached instance registries (one metrics_snapshot shows
  membership), ``start_replica`` fails loudly on a bound port, and
  ``DonefilePublisher.rollback_to`` re-applies a prior base atomically.
"""

import contextlib
import json
import os
import socket

import numpy as np
import pytest

from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
from paddlebox_tpu.core import faults
from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.core import monitor
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.serving import traceload
from paddlebox_tpu.serving.autopilot import (Autoscaler, CanaryController,
                                             ControllerState)
from paddlebox_tpu.serving.batcher import pack_bucketed
from paddlebox_tpu.serving.fleet import (HashRing, ServingFleet,
                                         route_key_hash, start_replica)
from paddlebox_tpu.serving.predictor import CTRPredictor, load_xbox_model
from paddlebox_tpu.serving.publisher import DonefilePublisher
from paddlebox_tpu.serving.service import PredictClient, PredictServer

SLOTS = ("u", "i")
DIM = 4
N_KEYS = 64


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@contextlib.contextmanager
def _flags(**kw):
    prev = {k: flagmod.flag(k) for k in kw}
    flagmod.set_flags(kw)
    try:
        yield
    finally:
        flagmod.set_flags(prev)


# -- trace replay: purity, shape, chaos schedule ------------------------------


def test_trace_replay_bit_identical_requests_and_routing():
    """Two generators from ONE config yield byte-identical request
    sequences AND bit-identical replica routing through the fleet's
    consistent-hash ring — the determinism the chaos drill and the
    bench's cross-run comparisons stand on."""
    cfg = traceload.TraceConfig(seed=7, duration_s=3.0, base_rps=40.0,
                                n_keys=500, hot_frac=0.02, hot_share=0.7)
    a = list(traceload.TraceGenerator(cfg).requests())
    b = list(traceload.TraceGenerator(cfg).requests())
    assert a == b
    assert len(a) > 50
    ring = HashRing(["rep-0", "rep-1", "rep-2"], 64)
    owners_a = [ring.lookup(route_key_hash(list(r.lines))) for r in a]
    owners_b = [ring.lookup(route_key_hash(list(r.lines))) for r in b]
    assert owners_a == owners_b
    assert len(set(owners_a)) == 3          # skew still spreads
    # A different seed is a different trace (the rid carries the seed).
    c = list(traceload.TraceGenerator(
        traceload.TraceConfig(seed=8, duration_s=3.0, base_rps=40.0,
                              n_keys=500)).requests())
    assert [r.lines for r in c[:20]] != [r.lines for r in a[:20]]
    assert a[0].rid.startswith("trace-7-")
    assert c[0].rid.startswith("trace-8-")


def test_trace_rate_diurnal_and_spike_shape():
    spike = traceload.ChaosEvent(at_s=4.0, kind="spike", duration_s=1.0,
                                 factor=10.0)
    gen = traceload.TraceGenerator(traceload.TraceConfig(
        seed=0, duration_s=10.0, base_rps=100.0, diurnal_amp=0.9,
        diurnal_period_s=10.0, chaos=(spike,)))
    # Peak near t=2.5 (sin max), trough near t=7.5 floored at 5%.
    assert gen.rate_at(2.5) == pytest.approx(190.0)
    assert gen.rate_at(7.5) >= 5.0
    # The spike window multiplies whatever the diurnal curve says.
    assert gen.rate_at(4.5) == pytest.approx(10.0 * gen.rate_at(3.9),
                                             rel=0.2)
    assert gen.rate_at(5.1) < gen.rate_at(4.5) / 5


def test_trace_hot_set_skew_and_quality_calibration():
    cfg = traceload.TraceConfig(seed=3, duration_s=20.0, base_rps=50.0,
                                n_keys=1000, hot_frac=0.01,
                                hot_share=0.8)
    gen = traceload.TraceGenerator(cfg)
    hot_n = max(1, int(cfg.n_keys * cfg.hot_frac))
    keys = []
    for req in gen.requests():
        for line in req.lines:
            keys.extend(int(tok.split(":")[1])
                        for tok in line.split()[1:])
    keys = np.asarray(keys)
    share = float((keys <= hot_n).mean())
    # hot_share of draws from the head, plus the uniform tail's overlap.
    assert 0.7 < share < 0.9, share
    # Skew calibrated from live observatory gauges; explicit kw wins.
    gauges = {"quality/slot_top_share/u": 0.6,
              "quality/slot_top_share/i": 0.2}
    assert traceload.skew_from_gauges(gauges) == pytest.approx(0.4)
    assert traceload.skew_from_gauges(
        {"quality/skew_top_share": 0.33}) == pytest.approx(0.33)
    assert traceload.skew_from_gauges({}) is None
    assert traceload.TraceConfig.from_quality(
        gauges).hot_share == pytest.approx(0.4)
    assert traceload.TraceConfig.from_quality(
        gauges, hot_share=0.9).hot_share == 0.9


def test_replay_virtual_clock_pacing_and_chaos_handlers():
    """The replay driver paces the virtual timeline against an injected
    clock and fires each non-spike chaos handler exactly once, in
    virtual-time order, between the requests that straddle it."""
    kill = traceload.ChaosEvent(at_s=1.0, kind="kill_replica", arg="r1")
    poison = traceload.ChaosEvent(at_s=2.0, kind="poison_delta",
                                  arg="20260807")
    gen = traceload.TraceGenerator(traceload.TraceConfig(
        seed=1, duration_s=3.0, base_rps=20.0,
        chaos=(poison, kill)))
    now = [0.0]
    fired = []
    sent = []

    def clock():
        return now[0]

    def sleep(dt):
        now[0] += dt

    out = traceload.replay(
        gen, lambda req: sent.append(req.t),
        handlers={"kill_replica": lambda ev: fired.append(("kill",
                                                           ev.arg)),
                  "poison_delta": lambda ev: fired.append(("poison",
                                                           ev.arg))},
        speed=2.0, clock=clock, sleep=sleep)
    assert out["sent"] == len(sent) == len(list(gen.requests()))
    assert out["events_fired"] == 2
    assert fired == [("kill", "r1"), ("poison", "20260807")]
    # speed=2 compresses the 3 s virtual trace into ~1.5 s of clock.
    assert now[0] == pytest.approx(sent[-1] / 2.0, abs=0.1)


# -- autoscaler: hysteresis, clamps, heal, crash resume -----------------------


class _Rep:
    def __init__(self, rid, inflight=0, routed=0):
        self.id = rid
        self.inflight = inflight
        self.routed = routed
        self.state = "healthy"
        self.admission = "ok"


class _Fleet:
    def __init__(self, rids):
        self._r = {rid: _Rep(rid) for rid in rids}

    def healthy(self):
        return sorted(self._r.values(), key=lambda r: r.id)

    def size(self):
        return len(self._r)

    def remove_replica(self, rid):
        self._r.pop(rid, None)

    def get(self, rid):
        return self._r.get(rid)


def _stats(p99=10.0, viol=0, fills=(0.8, 0.8)):
    return {"latency_ms": {"p99": p99}, "slo_violations": viol,
            "replicas": {f"r{i}": {"stats": {"batch_fill_frac": f}}
                         for i, f in enumerate(fills)}}


def test_autoscaler_flap_storm_one_action_per_cooldown():
    """Hysteresis: a p99 flap storm (breach on every poll) inside one
    cooldown window produces exactly ONE scale-out; the next window
    admits exactly one more."""
    spawns = []
    fleet = _Fleet(["a", "b"])
    sc = Autoscaler(fleet, lambda: _stats(p99=500.0),
                    spawn=lambda: spawns.append("n") or f"n{len(spawns)}",
                    alerts_fn=lambda: [], state=ControllerState(),
                    clock=lambda: 0.0)
    with _flags(serving_slo_p99_ms=100.0, autopilot_cooldown_s=10.0,
                autopilot_min_replicas=1, autopilot_max_replicas=8):
        for t in range(10):                      # one cooldown window
            sc.poll_once(now=100.0 + t)
        assert len(spawns) == 1
        sc.poll_once(now=111.0)                  # next window opens
        assert len(spawns) == 2
        assert all(a["kind"] == "scale_out" for a in sc.actions)


def test_autoscaler_alert_breach_and_max_clamp():
    """A firing burn alert is a breach on its own — and the max-replica
    clamp wins over any breach signal."""
    spawns = []
    firing = [{"name": "slo_violation_burn", "state": "firing"}]
    sc = Autoscaler(_Fleet(["a", "b"]), lambda: _stats(p99=1.0),
                    spawn=lambda: spawns.append("n") or "n",
                    alerts_fn=lambda: firing, state=ControllerState(),
                    clock=lambda: 0.0)
    with _flags(serving_slo_p99_ms=100.0, autopilot_cooldown_s=1.0,
                autopilot_min_replicas=1, autopilot_max_replicas=2):
        sc.poll_once(now=0.0)
        assert spawns == []                      # n == max: clamped
    with _flags(serving_slo_p99_ms=100.0, autopilot_cooldown_s=1.0,
                autopilot_min_replicas=1, autopilot_max_replicas=4):
        sc.poll_once(now=10.0)
        assert len(spawns) == 1
        assert "slo_violation_burn" in sc.actions[-1]["reason"]


def test_autoscaler_below_min_heals_without_latency_signal():
    """A kill that drops the healthy count under the floor re-grows
    capacity even when every latency sensor still reads clean."""
    spawns = []
    sc = Autoscaler(_Fleet(["a"]), lambda: _stats(p99=1.0),
                    spawn=lambda: spawns.append("n") or "heal-0",
                    alerts_fn=lambda: [], state=ControllerState(),
                    clock=lambda: 0.0)
    with _flags(serving_slo_p99_ms=1000.0, autopilot_cooldown_s=1.0,
                autopilot_min_replicas=2, autopilot_max_replicas=4):
        acts = sc.poll_once(now=10.0)
    assert len(spawns) == 1
    assert "min_replicas" in acts[0]["reason"]


def test_autoscaler_scale_in_drains_least_loaded_to_floor():
    fleet = _Fleet(["a", "b", "c"])
    fleet.get("a").inflight = 5
    fleet.get("c").inflight = 1
    retired = []
    sc = Autoscaler(fleet, lambda: _stats(p99=5.0, fills=(0.02, 0.03)),
                    spawn=lambda: "n", retire=retired.append,
                    alerts_fn=lambda: [], state=ControllerState(),
                    clock=lambda: 0.0)
    with _flags(serving_slo_p99_ms=1000.0, autopilot_cooldown_s=10.0,
                autopilot_min_replicas=1, autopilot_max_replicas=4,
                autopilot_scale_in_fill=0.1):
        sc.poll_once(now=100.0)
        assert retired == ["b"]                  # least (inflight, routed)
        sc.poll_once(now=101.0)                  # same window: held
        assert len(retired) == 1
        sc.poll_once(now=120.0)
        assert retired == ["b", "c"]
        sc.poll_once(now=140.0)                  # n == min: floor holds
        assert len(retired) == 2
    assert fleet.size() == 1


def test_autoscaler_crash_resume_no_double_spawn(tmp_path):
    """Kill the controller INSIDE the scale-out window (journal stamped,
    action not yet applied): a restarted controller on the same journal
    honors the cooldown — one window of lost capacity, never a double
    spawn."""
    path = str(tmp_path / "autopilot.json")
    spawns = []
    with _flags(serving_slo_p99_ms=100.0, autopilot_cooldown_s=10.0,
                autopilot_min_replicas=1, autopilot_max_replicas=8):
        sc = Autoscaler(_Fleet(["a"]), lambda: _stats(p99=500.0),
                        spawn=lambda: spawns.append("n") or "n",
                        alerts_fn=lambda: [],
                        state=ControllerState(path),
                        clock=lambda: 0.0)
        faults.configure("autopilot/scale_out:raise=IOError")
        with pytest.raises(OSError):
            sc.poll_once(now=100.0)
        assert spawns == []                      # died before the spawn
        faults.clear()
        # Restarted controller, same journal: inside the stamped window
        # the breach does NOT re-spawn; past it, exactly one spawn.
        sc2 = Autoscaler(_Fleet(["a"]), lambda: _stats(p99=500.0),
                         spawn=lambda: spawns.append("n") or "n",
                         alerts_fn=lambda: [],
                         state=ControllerState(path),
                         clock=lambda: 0.0)
        sc2.poll_once(now=105.0)
        assert spawns == []
        sc2.poll_once(now=110.5)
        assert len(spawns) == 1


def test_autoscaler_shard_repair_on_replica_lag():
    """Replication lag past FLAGS_alerts_replica_lag drives the shard
    repair actuator under its OWN cooldown group (a shard repair must
    not eat the replica-scale budget)."""
    repairs = []
    sc = Autoscaler(_Fleet(["a", "b"]), lambda: _stats(p99=1.0),
                    spawn=lambda: "n",
                    shard_repair=lambda: repairs.append("r") or {"ok": 1},
                    alerts_fn=lambda: [], state=ControllerState(),
                    clock=lambda: 0.0)
    try:
        monitor.set_gauge("multihost/replica_lag_p99", 50.0)
        with _flags(serving_slo_p99_ms=1000.0, autopilot_cooldown_s=10.0,
                    autopilot_min_replicas=1, autopilot_max_replicas=4,
                    alerts_replica_lag=8.0):
            sc.poll_once(now=100.0)
            sc.poll_once(now=101.0)              # same window: held
            assert len(repairs) == 1
            sc.poll_once(now=120.0)
            assert len(repairs) == 2
        assert all(a["kind"] == "shard_repair" for a in sc.actions)
    finally:
        monitor.set_gauge("multihost/replica_lag_p99", 0.0)


def test_controller_state_journal_roundtrip(tmp_path):
    path = str(tmp_path / "state.json")
    st = ControllerState(path)
    st.stamp("scale", 42.0)
    st.data["incumbent"] = {"day": "20260801"}
    st.save()
    st2 = ControllerState(path)
    assert st2.last_action_ts("scale") == 42.0
    assert st2.data["incumbent"]["day"] == "20260801"
    # Garbage journal: start fresh, never crash the controller.
    with open(path, "w") as f:
        f.write("{not json")
    assert ControllerState(path).last_action_ts("scale") == 0.0


# -- canary publish controller ------------------------------------------------


def _feed():
    return DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=16)


def _mk_canary_fleet(tmp_path, n=3):
    """n in-process replicas serving one published donefile base, in a
    ServingFleet the canary controller drives over real RPCs."""
    import jax
    model = DeepFM(slot_names=SLOTS, emb_dim=DIM, hidden=())
    dense = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(5)
    keys = np.arange(1, N_KEYS + 1, dtype=np.uint64)
    emb = rng.normal(size=(N_KEYS, DIM)).astype(np.float32) * 0.05
    w = rng.normal(size=(N_KEYS,)).astype(np.float32) * 0.05
    root = str(tmp_path / "publish")
    proto = CheckpointProtocol(root)

    def write_base(day, e, ww):
        d = proto.model_dir(day, 0)
        os.makedirs(d, exist_ok=True)
        np.savez(os.path.join(d, "embedding.xbox.npz"),
                 keys=keys, emb=e, w=ww)
        return d

    base = write_base("20260801", emb, w)
    proto.publish("20260801")
    fleet = ServingFleet()
    servers = {}
    for i in range(n):
        k2, e2, w2 = load_xbox_model(base, "embedding")
        pred = CTRPredictor(model, _feed(), k2, e2, w2, dense,
                            compute_dtype="float32")
        s = PredictServer("127.0.0.1:0", pred, replica_id=f"rep-{i}")
        servers[f"rep-{i}"] = s
        fleet.add_replica(f"rep-{i}", s.endpoint, ready=True)
    return fleet, servers, proto, write_base, (keys, emb, w)


def _probs(endpoint, lines):
    cli = PredictClient(endpoint)
    try:
        return cli.predict(lines)
    finally:
        cli.close()


_PROBE = ["0 u:3 i:9", "0 u:17 i:40", "0 u:60 i:2"]


def _plant_copc(servers, values):
    for rid, v in values.items():
        servers[rid].metrics.set_gauge("quality/copc", v)


def test_canary_stage_confine_and_promote(tmp_path):
    fleet, servers, proto, write_base, (keys, emb, w) = \
        _mk_canary_fleet(tmp_path)
    try:
        with _flags(autopilot_canary_replicas=1,
                    autopilot_canary_min_labels=0,
                    autopilot_canary_copc_margin=0.2,
                    autopilot_canary_timeout_s=60.0):
            ctrl = CanaryController(
                fleet, str(tmp_path / "publish"),
                state=ControllerState(str(tmp_path / "ap.json")),
                clock=lambda: 100.0)
            # The base the fleet stood up from is the incumbent, not a
            # canary.
            assert ctrl.poll_once() is None
            assert ctrl.incumbent()["day"] == "20260801"
            before = _probs(servers["rep-1"].endpoint, _PROBE)

            write_base("20260802", -emb, w)
            proto.publish("20260802")
            assert ctrl.poll_once() == "canary"
            can = ctrl.state.data["canary"]
            assert can["canary_ids"] == ["rep-0"]     # FLAGS-sized subset
            # Confined: the canary replica serves the NEW base, the
            # incumbents still serve the old one.
            canary_probs = _probs(servers["rep-0"].endpoint, _PROBE)
            assert not np.allclose(canary_probs, before)
            np.testing.assert_array_equal(
                _probs(servers["rep-1"].endpoint, _PROBE), before)
            # No verdict until both sides report COPC.
            assert ctrl.poll_once() is None
            _plant_copc(servers, {"rep-0": 1.01, "rep-1": 0.99,
                                  "rep-2": 1.0})
            n_promote = monitor.get("autopilot/actions/canary_promote")
            assert ctrl.poll_once() == "promote"
            # Full fanout: every replica now serves the canary's model;
            # the new base is the incumbent.
            for s in servers.values():
                np.testing.assert_array_equal(
                    _probs(s.endpoint, _PROBE), canary_probs)
            assert ctrl.incumbent()["day"] == "20260802"
            assert ctrl.state.data["canary"] is None
            assert monitor.get("autopilot/actions/canary_promote") == \
                n_promote + 1
            assert ctrl.reports[-1]["verdict"] == "promote"
            assert ctrl.poll_once() is None           # seen, not re-staged
    finally:
        for s in servers.values():
            s.stop()


def test_canary_rollback_confines_poisoned_base(tmp_path, capsys):
    fleet, servers, proto, write_base, (keys, emb, w) = \
        _mk_canary_fleet(tmp_path)
    try:
        with _flags(autopilot_canary_replicas=1,
                    autopilot_canary_min_labels=0,
                    autopilot_canary_copc_margin=0.2,
                    autopilot_canary_timeout_s=60.0):
            ctrl = CanaryController(
                fleet, str(tmp_path / "publish"),
                state=ControllerState(str(tmp_path / "ap.json")),
                clock=lambda: 100.0)
            ctrl.poll_once()
            incumbent_probs = _probs(servers["rep-0"].endpoint, _PROBE)
            write_base("20260803", emb + 5.0, w + 5.0)   # poisoned
            proto.publish("20260803")
            assert ctrl.poll_once() == "canary"
            assert not np.allclose(
                _probs(servers["rep-0"].endpoint, _PROBE),
                incumbent_probs)
            # Breached calibration on the canary side only.
            _plant_copc(servers, {"rep-0": 0.5, "rep-1": 1.0,
                                  "rep-2": 1.0})
            n_rb = monitor.get("serving/hotswap_rollbacks")
            assert ctrl.poll_once() == "rollback"
            # The incumbent base is RESTORED on the canary replica; the
            # poisoned model never reached the other replicas.
            np.testing.assert_array_equal(
                _probs(servers["rep-0"].endpoint, _PROBE),
                incumbent_probs)
            np.testing.assert_array_equal(
                _probs(servers["rep-2"].endpoint, _PROBE),
                incumbent_probs)
            assert monitor.get("serving/hotswap_rollbacks") == n_rb + 1
            assert ctrl.incumbent()["day"] == "20260801"
            rep = ctrl.reports[-1]
            assert rep["verdict"] == "rollback"
            assert rep["objective"] == "copc"
            # One machine-readable verdict line.
            lines = [ln for ln in capsys.readouterr().out.splitlines()
                     if ln.startswith("autopilot_report ")]
            assert lines, "no autopilot_report line emitted"
            parsed = json.loads(lines[-1].split(" ", 1)[1])
            assert parsed["verdict"] == "rollback"
            assert parsed["objective"] == "copc"
            # The bad base stays seen: never re-staged.
            assert ctrl.poll_once() is None
    finally:
        for s in servers.values():
            s.stop()


def test_canary_crash_resume_never_half_promoted(tmp_path):
    """Kill the controller inside the promote (and then the rollback)
    faultpoint window: the journaled phase re-drives idempotently on
    restart — the fleet always converges to all-new or all-incumbent,
    never a half-promoted split."""
    fleet, servers, proto, write_base, (keys, emb, w) = \
        _mk_canary_fleet(tmp_path)
    path = str(tmp_path / "ap.json")
    try:
        with _flags(autopilot_canary_replicas=1,
                    autopilot_canary_min_labels=0,
                    autopilot_canary_copc_margin=0.2,
                    autopilot_canary_timeout_s=60.0):
            ctrl = CanaryController(fleet, str(tmp_path / "publish"),
                                    state=ControllerState(path),
                                    clock=lambda: 100.0)
            ctrl.poll_once()
            write_base("20260804", -emb, w)
            proto.publish("20260804")
            assert ctrl.poll_once() == "canary"
            canary_probs = _probs(servers["rep-0"].endpoint, _PROBE)
            _plant_copc(servers, {"rep-0": 1.0, "rep-1": 1.0,
                                  "rep-2": 1.0})
            faults.configure("autopilot/canary_promote:raise=IOError")
            with pytest.raises(OSError):
                ctrl.poll_once()
            faults.clear()
            # Restart on the same journal: the promote re-drives.
            ctrl2 = CanaryController(fleet, str(tmp_path / "publish"),
                                     state=ControllerState(path),
                                     clock=lambda: 200.0)
            assert ctrl2.poll_once() == "promote"
            for s in servers.values():
                np.testing.assert_array_equal(
                    _probs(s.endpoint, _PROBE), canary_probs)
            assert ctrl2.incumbent()["day"] == "20260804"
            assert ctrl2.poll_once() is None

            # Same contract for a rollback killed mid-flight.
            write_base("20260805", emb + 5.0, w + 5.0)
            proto.publish("20260805")
            assert ctrl2.poll_once() == "canary"
            _plant_copc(servers, {"rep-0": 0.4, "rep-1": 1.0,
                                  "rep-2": 1.0})
            faults.configure("autopilot/canary_rollback:raise=IOError")
            with pytest.raises(OSError):
                ctrl2.poll_once()
            faults.clear()
            ctrl3 = CanaryController(fleet, str(tmp_path / "publish"),
                                     state=ControllerState(path),
                                     clock=lambda: 300.0)
            assert ctrl3.poll_once() == "rollback"
            for s in servers.values():
                np.testing.assert_array_equal(
                    _probs(s.endpoint, _PROBE), canary_probs)
            assert ctrl3.incumbent()["day"] == "20260804"
    finally:
        for s in servers.values():
            s.stop()


# -- fleet topology gauges + loud port conflict -------------------------------


def test_fleet_topology_gauges_in_attached_registry():
    fleet = ServingFleet()
    reg = monitor.Monitor()
    fleet.attach_registry(reg)
    fleet.add_replica("a", "127.0.0.1:1", ready=True)
    fleet.add_replica("b", "127.0.0.1:2", ready=False)
    snap = reg.snapshot_all()
    g = snap["gauges"]
    assert g["fleet/topology_epoch"] == float(fleet.epoch)
    assert g["fleet/replica_state/a"] == 1.0      # healthy
    assert g["fleet/replica_state/b"] == 0.0      # joining
    epoch0 = fleet.epoch
    fleet.remove_replica("a")
    g = reg.snapshot_all()["gauges"]
    assert g["fleet/topology_epoch"] == float(fleet.epoch) > epoch0
    assert g["fleet/replica_state/a"] == 3.0      # left the fleet
    # The process-global registry mirrors the same picture.
    assert monitor.get_gauge("fleet/replica_state/b") == 0.0


def test_start_replica_bound_port_fails_loudly():
    """A supervisor restarting a replica onto a port the old process
    still holds must get an immediate error, not a predictor build
    followed by a hang."""
    holder = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    holder.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    holder.bind(("127.0.0.1", 0))
    holder.listen(1)
    port = holder.getsockname()[1]
    try:
        with pytest.raises(RuntimeError, match="already bound"):
            start_replica(None, None,
                          endpoint=f"127.0.0.1:{port}")
    finally:
        holder.close()


# -- publisher reverse gear ---------------------------------------------------


def test_publisher_rollback_to_restores_base(tmp_path):
    import jax
    model = DeepFM(slot_names=SLOTS, emb_dim=DIM, hidden=())
    dense = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(9)
    keys = np.arange(1, N_KEYS + 1, dtype=np.uint64)
    emb = rng.normal(size=(N_KEYS, DIM)).astype(np.float32) * 0.05
    w = rng.normal(size=(N_KEYS,)).astype(np.float32) * 0.05
    root = str(tmp_path / "publish")
    proto = CheckpointProtocol(root)
    base_dir = proto.model_dir("20260801", 0)
    os.makedirs(base_dir, exist_ok=True)
    np.savez(os.path.join(base_dir, "embedding.xbox.npz"),
             keys=keys, emb=emb, w=w)
    proto.publish("20260801")

    pred = CTRPredictor(model, _feed(), keys, emb, w, dense,
                        compute_dtype="float32")

    def probs():
        return pred.predict(pack_bucketed(
            parse_lines(_PROBE, _feed()), _feed()))

    base_probs = probs()
    pub = DonefilePublisher(pred, root)   # base already seen: provenance
    delta_dir = proto.model_dir("20260801", 1)
    os.makedirs(delta_dir, exist_ok=True)
    np.savez(os.path.join(delta_dir, "embedding.delta.npz"),
             keys=keys, emb=emb + 1.0, w=w + 1.0)
    proto.publish("20260801", pass_id=1)
    assert pub.poll_once() == 1
    assert not np.allclose(probs(), base_probs)

    base_rec = [r for r in proto.records() if r.pass_id == 0][0]
    n_rb = monitor.get("serving/hotswap_rollbacks")
    rows = pub.rollback_to(base_rec)
    assert rows >= 0
    np.testing.assert_array_equal(probs(), base_probs)
    assert monitor.get("serving/hotswap_rollbacks") == n_rb + 1
    # The reverse gear marks the record seen: the forward tail does not
    # immediately re-apply it as new work.
    assert pub.poll_once() == 0
