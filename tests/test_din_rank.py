"""DIN-Rank model tests: rank_offset construction from pv group ids and
end-to-end learning of an in-pv context signal that a peer-blind model
cannot capture."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.models import DINRank, build_rank_offset


def test_build_rank_offset_structure():
    gids = np.asarray([7, 7, 7, 9, 9, 3], np.uint64)
    ro = build_rank_offset(gids, max_rank=4)
    # ranks within each contiguous group
    np.testing.assert_array_equal(ro[:, 0], [1, 2, 3, 1, 2, 1])
    # row 0's peers: rows 1 (rank 2) and 2 (rank 3)
    assert (ro[0, 1], ro[0, 2]) == (2, 1)
    assert (ro[0, 3], ro[0, 4]) == (3, 2)
    assert ro[0, 5] == 0  # padding
    # singleton group: no peers
    assert (ro[5, 1:] == 0).all()


def test_build_rank_offset_respects_valid_and_cap():
    gids = np.asarray([1] * 6, np.uint64)
    valid = np.asarray([True, False, True, True, True, True])
    ro = build_rank_offset(gids, max_rank=3, valid=valid)
    assert ro[1, 0] == 0                 # invalid row gets no rank
    np.testing.assert_array_equal(ro[[0, 2, 3], 0], [1, 2, 3])
    assert ro[5, 0] == 0                 # beyond max_rank positions drop


def test_din_rank_learns_peer_signal():
    """Label = 1 iff the instance's OWN feature is weaker than its pv
    peer's — only visible through rank attention."""
    rng = np.random.default_rng(0)
    model = DINRank(slot_names=("s",), emb_dim=4, max_rank=2,
                    att_dim=8, hidden=(16,))
    params = model.init(jax.random.PRNGKey(0))
    b = 32  # 16 pvs of 2

    def make_batch():
        strength = rng.normal(size=(b,)).astype(np.float32)
        emb = np.zeros((b, 4), np.float32)
        emb[:, 0] = strength
        segs = np.arange(b, dtype=np.int32)
        gids = np.repeat(np.arange(b // 2), 2).astype(np.uint64)
        labels = np.zeros((b,), np.float32)
        for i in range(0, b, 2):
            labels[i] = float(strength[i] < strength[i + 1])
            labels[i + 1] = float(strength[i + 1] < strength[i])
        ro = build_rank_offset(gids, max_rank=2)
        return (jnp.asarray(emb), jnp.asarray(segs), jnp.asarray(ro),
                jnp.asarray(labels))

    @jax.jit
    def step(params, emb, segs, ro, labels):
        def loss_fn(params):
            logits = model.apply(
                params, {"s": emb}, {"s": jnp.zeros(b)}, {"s": segs},
                batch_size=b, rank_offset=ro)
            return jnp.mean(
                jnp.maximum(logits, 0) - logits * labels
                + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        loss, g = jax.value_and_grad(loss_fn)(params)
        return jax.tree.map(lambda p, gg: p - 0.1 * gg, params, g), loss

    losses = []
    for _ in range(300):
        emb, segs, ro, labels = make_batch()
        params, loss = step(params, emb, segs, ro, labels)
        losses.append(float(loss))
    assert losses[-1] < 0.4 < losses[0]

    # peer-blind ablation (no rank_offset) cannot separate the labels
    emb, segs, ro, labels = make_batch()
    logits_blind = model.apply(params, {"s": emb}, {"s": jnp.zeros(b)},
                               {"s": segs}, batch_size=b)
    pred_blind = (np.asarray(logits_blind) > 0)
    acc_blind = (pred_blind == np.asarray(labels)).mean()
    logits_att = model.apply(params, {"s": emb}, {"s": jnp.zeros(b)},
                             {"s": segs}, batch_size=b, rank_offset=ro)
    acc_att = ((np.asarray(logits_att) > 0) == np.asarray(labels)).mean()
    assert acc_att > 0.85
    assert acc_att > acc_blind + 0.2
