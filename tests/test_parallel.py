"""Parallelism suite tests: TP layers, ring/Ulysses attention, pipeline,
MoE, ZeRO specs — each verified against a single-device dense reference
(the reference's hybrid_parallel_mp_model.py-style parity tests run as
subprocess clusters; here the 8-device virtual mesh does it in-process).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.parallel import (HybridTopology, build_mesh, moe, pp, sp,
                                    tp, zero)


# ---------------------------------------------------------------------------
# TP layers
# ---------------------------------------------------------------------------

def test_vocab_parallel_embedding(devices8):
    mesh = build_mesh(HybridTopology(mp=8))
    vocab, dim = 64, 16
    params, specs = tp.vocab_parallel_embedding_init(
        jax.random.PRNGKey(0), vocab, dim)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, vocab, (4, 7)))

    f = jax.shard_map(
        functools.partial(tp.vocab_parallel_embedding, axis="mp"),
        mesh=mesh, in_specs=({"table": specs["table"]}, P()),
        out_specs=P(), check_vma=False)
    out = f(params, ids)
    ref = params["table"][ids]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_column_row_parallel_linear_composition(devices8):
    """Column(gather=False) -> Row(parallel in) == dense two-layer."""
    mesh = build_mesh(HybridTopology(mp=8))
    rng = jax.random.PRNGKey(1)
    r1, r2 = jax.random.split(rng)
    cp, cspec = tp.column_parallel_linear_init(r1, 32, 64)
    rp, rspec = tp.row_parallel_linear_init(r2, 64, 16)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 32))

    def f(cp, rp, x):
        h = tp.column_parallel_linear(cp, x, axis="mp")
        return tp.row_parallel_linear(rp, h, axis="mp")

    fm = jax.shard_map(f, mesh=mesh,
                       in_specs=(cspec, rspec, P()),
                       out_specs=P(), check_vma=False)
    out = fm(cp, rp, x)
    ref = (x @ cp["w"] + cp["b"]) @ rp["w"] + rp["b"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_parallel_cross_entropy(devices8):
    mesh = build_mesh(HybridTopology(mp=8))
    t, v = 12, 64
    logits = jax.random.normal(jax.random.PRNGKey(3), (t, v))
    labels = jnp.asarray(np.random.default_rng(1).integers(0, v, (t,)))

    f = jax.shard_map(
        functools.partial(tp.parallel_cross_entropy, axis="mp"),
        mesh=mesh, in_specs=(P(None, "mp"), P()),
        out_specs=P(), check_vma=False)
    loss = f(logits, labels)
    # Dense reference.
    logz = jax.nn.logsumexp(logits, axis=-1)
    ref = logz - logits[jnp.arange(t), labels]
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# Sequence parallelism
# ---------------------------------------------------------------------------

def _dense_attention(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if causal:
        n = q.shape[1]
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(devices8, causal):
    mesh = build_mesh(HybridTopology(sp=8))
    b, s, h, d = 2, 64, 4, 8
    rng = np.random.default_rng(5)
    q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
               for _ in range(3))

    f = jax.shard_map(
        functools.partial(sp.ring_attention, axis="sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False)
    out = f(q, k, v)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_exact(devices8, causal):
    mesh = build_mesh(HybridTopology(sp=8))
    b, s, h, d = 2, 64, 8, 4
    rng = np.random.default_rng(6)
    q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
               for _ in range(3))

    f = jax.shard_map(
        functools.partial(sp.ulysses_attention, axis="sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False)
    out = f(q, k, v)
    ref = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_flow(devices8):
    """Autodiff through the ring (training usability)."""
    mesh = build_mesh(HybridTopology(sp=8))
    b, s, h, d = 1, 32, 2, 4
    q = jax.random.normal(jax.random.PRNGKey(7), (b, s, h, d))

    def loss(q):
        f = jax.shard_map(
            functools.partial(sp.ring_attention, axis="sp", causal=True),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False)
        return jnp.sum(f(q, q, q) ** 2)

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).sum() > 0


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------

def test_gpipe_matches_sequential(devices8):
    mesh = build_mesh(HybridTopology(pp=8))
    f_dim = 16
    rng = jax.random.PRNGKey(8)
    stage_params = []
    for i in range(8):
        rng, sub = jax.random.split(rng)
        w = jax.random.normal(sub, (f_dim, f_dim)) * 0.3
        stage_params.append({"w": w})
    stacked = pp.stack_stage_params(stage_params)

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    x_mb = jax.random.normal(jax.random.PRNGKey(9), (4, 8, f_dim))  # M=4

    run = pp.make_pipeline_fn(mesh, stage_fn, stacked)
    out = run(stacked, x_mb)

    ref = x_mb
    for p in stage_params:
        ref = jnp.tanh(ref @ p["w"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_gpipe_backward(devices8):
    mesh = build_mesh(HybridTopology(pp=8))
    f_dim = 8
    stage_params = [{"w": jax.random.normal(jax.random.PRNGKey(i),
                                            (f_dim, f_dim)) * 0.3}
                    for i in range(8)]
    stacked = pp.stack_stage_params(stage_params)
    x_mb = jax.random.normal(jax.random.PRNGKey(99), (2, 4, f_dim))

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    pspecs = pp.stage_specs(stacked)

    def loss(stacked, x_mb):
        f = jax.shard_map(
            lambda sp_, x: pp.gpipe_apply(
                stage_fn, jax.tree.map(lambda a: a[0], sp_), x),
            mesh=mesh, in_specs=(pspecs, P()), out_specs=P(),
            check_vma=False)
        return jnp.sum(f(stacked, x_mb) ** 2)

    g = jax.grad(loss)(stacked, x_mb)
    g_flat = np.asarray(g["w"])
    assert np.isfinite(g_flat).all()
    # Every stage's params get gradient.
    assert (np.abs(g_flat).reshape(8, -1).sum(axis=1) > 0).all()


# ---------------------------------------------------------------------------
# MoE
# ---------------------------------------------------------------------------

def test_moe_dispatch_combine(devices8):
    mesh = build_mesh(HybridTopology(ep=8))
    f_dim, e_local = 16, 2  # 16 experts over 8 devices
    t_total = 8 * 32
    rng = jax.random.PRNGKey(11)
    k1, k2, k3 = jax.random.split(rng, 3)
    gate_w = jax.random.normal(k1, (f_dim, 16)) * 0.5
    # Identity-ish experts: expert e multiplies by (1 + e/10).
    expert_scale = (1.0 + jnp.arange(16) / 10.0)
    expert_params = {"scale": expert_scale.reshape(8, 2)}  # [dev, local]
    x = jax.random.normal(k3, (t_total, f_dim))

    def expert_fn(params_e, tokens):
        return tokens * params_e["scale"]

    def f(gate_w, expert_params, x):
        return moe.moe_layer(gate_w, expert_params, expert_fn, x,
                             axis="ep", capacity_factor=4.0)

    fm = jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(), {"scale": P("ep")}, P("ep")),
        out_specs=(P("ep"), P()), check_vma=False)
    y, aux = fm(gate_w, {"scale": expert_scale.reshape(16,)}, x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
    assert float(aux) > 0

    # Reference: dense top-2 mixture with ample capacity.
    logits = x @ gate_w
    gates = jax.nn.softmax(logits, axis=-1)
    top2 = jnp.argsort(gates, axis=-1)[:, -2:]
    ref = np.zeros_like(np.asarray(x))
    gn = np.asarray(gates)
    for t in range(t_total):
        e1, e2 = int(top2[t, 1]), int(top2[t, 0])
        w1, w2 = gn[t, e1], gn[t, e2]
        zn = w1 + w2
        ref[t] = (w1 / zn * np.asarray(x[t]) * (1 + e1 / 10.0) +
                  w2 / zn * np.asarray(x[t]) * (1 + e2 / 10.0))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-3, atol=1e-4)


# ---------------------------------------------------------------------------
# ZeRO specs
# ---------------------------------------------------------------------------

def test_zero_specs_and_shard(devices8):
    mesh = build_mesh(HybridTopology(sharding=8))
    params = {
        "big": jnp.zeros((1024, 64)),     # sharded (dim 0 divisible)
        "small": jnp.zeros((4, 4)),       # replicated (too small)
        "odd": jnp.zeros((17, 131072)),   # dim1 not divisible... 131072%8==0
    }
    specs = zero.zero_specs(params, mesh)
    assert specs["big"] == P("sharding", None)
    assert specs["small"] == P()
    assert specs["odd"] == P(None, "sharding")

    sharded = zero.shard_tree(params, mesh)
    assert sharded["big"].sharding.spec == P("sharding", None)
    # addressable shard is 1/8 of rows
    assert sharded["big"].addressable_shards[0].data.shape == (128, 64)
