"""Worker payload for the REAL-PROCESS serving fleet drill: one
replica process that warms against the shared shard tier, registers its
serving endpoint through the elastic heartbeat meta (the same discovery
path the router watches), and serves until killed — the SIGKILL target
of ``tests/test_fleet_drill.py``.

Determinism contract with the drill: every replica builds the SAME
model (fixed init seed) over the SAME shared shard tier, so any two
replicas answer bit-identical probabilities for the same lines — which
is what lets the drill assert a joiner against an incumbent.

Usage: fleet_replica_worker.py <elastic_root> <host_id>
       <shard_endpoints_csv> <ready_file>
"""

import os
import sys
import time

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

SLOTS = ("u", "i")
DIM = 8


def main() -> None:
    elastic_root, host_id, shard_eps, ready_file = sys.argv[1:5]

    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.serving.fleet import start_replica

    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=16)
    model = DeepFM(slot_names=SLOTS, emb_dim=DIM, hidden=())
    dense = model.init(jax.random.PRNGKey(0))

    # PBX_FLEET_SHARD_REPLICAS > 1: the shared shard tier is replicated
    # (the distributed-trace drill kills a shard primary under traffic
    # and expects this replica's miss reads to fail over).
    shard_replicas = int(os.environ.get("PBX_FLEET_SHARD_REPLICAS", "1"))
    # PBX_FLEET_BASE_EXPORT: donefile base dir to stand the replica up
    # from (the autopilot chaos drill's canary/rollback target) — keys
    # in the export serve warm, everything else still resolves misses
    # against the shard tier.
    base_export = os.environ.get("PBX_FLEET_BASE_EXPORT") or None
    kw = {"base_export": base_export} if base_export else {"dim": DIM}
    server, manager = start_replica(
        model, feed,
        dense_params=dense,
        shard_endpoints=[e for e in shard_eps.split(",") if e],
        shard_replicas=shard_replicas,
        hbm_rows=24,
        elastic_root=elastic_root, host_id=host_id,
        warm_lines=["0 u:1 i:2", "0 u:3 i:4"],
        compute_dtype="float32", **kw)

    tmp = ready_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(server.endpoint)
    os.replace(tmp, ready_file)

    # Serve until killed (the drill SIGKILLs us) or politely stopped.
    try:
        while True:
            time.sleep(0.2)
    finally:
        if manager is not None:
            manager.stop()
        server.stop()


if __name__ == "__main__":
    main()
