"""Subprocess worker for the streaming kill -9 crash drill
(tests/test_stream_drill.py, the PR-5 crash_drill pattern applied to
the stream tier): consume a fixed event-log directory through
StreamRunner — resume() from the durable cursor, one flushed poll, day
close — and write the final state digests atomically. The harness
SIGKILLs this process at a chosen ``stream/*`` faultpoint, reruns it
clean, and byte-compares against a never-killed reference: the cursor
contract means no event is ever lost or trained twice."""

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SLOTS = ("user", "item")
BS = 32
FILES = 4
PASS_EVENTS = 2 * BS          # two files per carved pass


def write_events(log_dir: str) -> None:
    """Deterministic fixed event log (shared by harness + reference)."""
    import numpy as np
    rng = np.random.default_rng(29)
    os.makedirs(log_dir, exist_ok=True)
    for i in range(FILES):
        tmp = os.path.join(log_dir, f".e{i:03d}.log.tmp")
        with open(tmp, "w") as f:
            for _ in range(BS):
                toks = " ".join(f"{s}:{rng.integers(1, 150)}"
                                for s in SLOTS)
                f.write(f"{int(rng.random() < 0.3)} {toks}\n")
        os.replace(tmp, os.path.join(log_dir, f"e{i:03d}.log"))


def _digest(arrays) -> str:
    import numpy as np
    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


# -- tail mode (FLAGS_stream_tail_bytes): ONE growing file ------------------

TAIL_STAGES = 3


def _stage_bytes(stage: int) -> bytes:
    """Deterministic event lines of one append stage."""
    import numpy as np
    rng = np.random.default_rng(1000 + stage)
    out = []
    for _ in range(BS):
        toks = " ".join(f"{s}:{rng.integers(1, 150)}" for s in SLOTS)
        out.append(f"{int(rng.random() < 0.3)} {toks}\n")
    return "".join(out).encode()


def append_stage(log_dir: str, stage: int) -> None:
    """Append stage ``stage``'s bytes IF not already appended (the
    resumed process replays the same schedule; file size tells which
    stages the killed run already landed — appends only ever happen at
    stage boundaries because the faultpoints sit inside poll_once)."""
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, "live.log")
    want = sum(len(_stage_bytes(s)) for s in range(stage + 1))
    have = os.path.getsize(path) if os.path.exists(path) else 0
    if have >= want:
        return
    with open(path, "ab") as f:
        f.write(_stage_bytes(stage))


def main(log_dir: str, out_dir: str, result: str,
         mode: str = "segments") -> None:
    import numpy as np

    import jax

    from paddlebox_tpu.core import flags
    from paddlebox_tpu.data import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.stream import StreamRunner
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    if mode == "tail":
        # Byte-offset cursor mode: one growing file, one carved pass
        # per appended stage, cut mid-file at the last newline.
        flags.set_flags({"stream_tail_bytes": True,
                         "stream_pass_events": BS,
                         "stream_pass_window_s": 0.0})
    else:
        flags.set_flags({"stream_pass_events": PASS_EVENTS,
                         "stream_pass_window_s": 0.0})
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=BS)
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10))
    trainer.init(seed=0)
    # ONE reader thread: no-shuffle parity needs deterministic chunk
    # order across the kill/resume/reference runs (see test_stream.py).
    runner = StreamRunner(trainer, feed, out_dir, log_dir=log_dir,
                          shuffle=False, num_reader_threads=1)
    runner.resume()
    if mode == "tail":
        for stage in range(TAIL_STAGES):
            append_stage(log_dir, stage)
            runner.poll_once(flush=True)
    else:
        runner.poll_once(flush=True)
    runner.end_day()

    store = trainer.engine.store
    keys = np.sort(store.key_stats()[0])
    vals = store.pull_for_pass(keys)
    payload = {
        "num_features": int(store.num_features),
        "store_digest": _digest([keys] + [vals[f] for f in sorted(vals)]),
        "dense_digest": _digest(
            list(jax.tree.leaves(jax.device_get(trainer.params)))
            + list(jax.tree.leaves(jax.device_get(trainer.opt_state)))),
        "records": [[r.day, r.pass_id] for r in runner.ckpt.records()],
        "manifests": [m.to_dict() for m in runner.cursor.manifests],
    }
    tmp = result + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, result)


if __name__ == "__main__":
    main(*sys.argv[1:4],
         mode=(sys.argv[4] if len(sys.argv) > 4 else "segments"))
