"""sorted_scatter_accumulate (CopyForPush-class Pallas kernel) vs the XLA
scatter reference — interpret mode on CPU; same code compiles for TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.ops.pallas_kernels.sorted_scatter import (
    BLOCK, UCAP, sorted_scatter_accumulate)


def _ref(rows, payload, num_rows):
    keep = rows < num_rows
    safe = np.where(keep, rows, 0)
    contrib = np.where(keep[:, None], payload, 0.0)
    out = np.zeros((num_rows, payload.shape[1]), np.float32)
    np.add.at(out, safe, contrib)
    out[~np.isin(np.arange(num_rows), rows[keep])] *= 1.0
    # np.add.at added dropped rows' zero contribs at row 0 — they're zero.
    return out


@pytest.mark.parametrize("num_rows,n", [(BLOCK, 1000),
                                        (3 * BLOCK + 17, 20_000)])
def test_matches_xla_scatter(num_rows, n):
    rng = np.random.default_rng(0)
    rows = rng.integers(0, num_rows, n).astype(np.int32)
    payload = rng.normal(size=(n, 12)).astype(np.float32)
    got = sorted_scatter_accumulate(jnp.asarray(rows),
                                    jnp.asarray(payload), num_rows,
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(got), _ref(rows, payload,
                                                     num_rows),
                               rtol=1e-5, atol=1e-5)


def test_sentinel_rows_dropped():
    rng = np.random.default_rng(1)
    num_rows = BLOCK
    rows = rng.integers(0, num_rows, 500).astype(np.int32)
    # A third of entries carry the drop sentinel (trash/padding).
    rows[::3] = num_rows
    payload = rng.normal(size=(500, 8)).astype(np.float32)
    got = sorted_scatter_accumulate(jnp.asarray(rows),
                                    jnp.asarray(payload), num_rows,
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(got), _ref(rows, payload,
                                                     num_rows),
                               rtol=1e-5, atol=1e-5)


def test_hot_row_falls_back_to_xla_scatter():
    """More than UCAP updates on one row: the kernel budget would
    overflow, so the cond must take the exact XLA path."""
    rng = np.random.default_rng(2)
    num_rows = BLOCK
    n = UCAP + 2048
    rows = np.full((n,), 7, np.int32)        # everything hits row 7
    payload = rng.normal(size=(n, 4)).astype(np.float32)
    got = sorted_scatter_accumulate(jnp.asarray(rows),
                                    jnp.asarray(payload), num_rows,
                                    interpret=True)
    ref = _ref(rows, payload, num_rows)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-4)


def test_push_local_kernel_path_matches_xla(monkeypatch):
    """Full push_local through the Pallas (interpret) accumulate equals
    the XLA-scatter path — table values, states, and stats."""
    import jax.numpy as jnp
    from paddlebox_tpu.core import flags as flagmod
    from paddlebox_tpu.embedding.lookup import push_local
    from paddlebox_tpu.embedding.optimizers import SparseAdagrad
    from paddlebox_tpu.embedding.table import PassTable

    rng = np.random.default_rng(3)
    rps, d = 300, 4
    ke, kw = 1, 1
    w_width = d + 3 + ke + kw
    vals = rng.normal(size=(rps + 1, w_width)).astype(np.float32)
    vals[rps, :d + 3] = 0.0          # trash row pull columns zero
    n = 256
    rows = rng.integers(0, rps, n).astype(np.int32)
    rows[::5] = rps                  # padding entries -> trash row
    g_emb = rng.normal(size=(n, d)).astype(np.float32)
    g_w = rng.normal(size=(n,)).astype(np.float32)
    shows = (rows != rps).astype(np.float32)
    clicks = shows * (rng.random(n) < 0.4)
    g_emb[rows == rps] = 0.0
    g_w[rows == rps] = 0.0

    def run(mode):
        flagmod.set_flags({"sparse_scatter_kernel": mode})
        try:
            table = PassTable(vals=jnp.asarray(vals), rows_per_shard=rps,
                              num_shards=1, dim=d, ke=ke, kw=kw)
            out = push_local(table, jnp.asarray(rows), jnp.asarray(g_emb),
                             jnp.asarray(g_w), jnp.asarray(shows),
                             jnp.asarray(clicks), axis="dp",
                             opt=SparseAdagrad())
            return np.asarray(out.vals)
        finally:
            flagmod.set_flags({"sparse_scatter_kernel": "auto"})

    a = run("xla")
    b = run("interpret")
    # Trash-row optimizer state may differ (kernel drops trash updates;
    # the XLA path counts them) — everything consumable must match.
    np.testing.assert_allclose(b[:rps], a[:rps], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(b[rps, :d + 3], a[rps, :d + 3], atol=0)


def test_sentinel_stays_off_the_books_at_non_multiple_num_rows():
    """num_rows NOT a multiple of BLOCK + thousands of concentrated
    sentinel entries: they must neither corrupt the result nor count
    toward any block's run (which would permanently force the XLA
    fallback)."""
    rng = np.random.default_rng(4)
    num_rows = BLOCK + 1           # rows_per_shard+1 shape, the real case
    n = 9000                       # > UCAP sentinels if they clustered
    rows = rng.integers(0, num_rows, n).astype(np.int32)
    rows[::2] = num_rows           # half the entries are padding
    payload = rng.normal(size=(n, 6)).astype(np.float32)
    got = sorted_scatter_accumulate(jnp.asarray(rows),
                                    jnp.asarray(payload), num_rows,
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(got),
                               _ref(rows, payload, num_rows),
                               rtol=1e-5, atol=1e-5)
