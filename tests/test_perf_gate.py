"""The cross-run perf regression gate (tools/perf_gate.py) as a tier-1
smoke: the gate must exit 0 on a baseline-identical report, nonzero on
a synthetically-regressed one (throughput, stage share, tail quantile,
device idle fraction), honor tolerances/directions, and its built-in
--smoke self-check must pass — so a perf regression fails THIS suite,
not a future bench recording.

No jax import: the gate is pure stdlib and runs in milliseconds.
"""

import copy
import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
perf_gate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(perf_gate)


# A realistic bench-record shape (BENCH_r02-style + the round-11
# bottleneck/quantile fields).
BASE = {
    "metric": "deepfm_ctr_e2e_samples_per_sec_per_chip",
    "value": 8587.0,
    "unit": "samples/s/chip",
    "vs_baseline": 1.0,
    "device_only_per_chip": 55000.0,
    "e2e_over_device_only": 0.156,
    "store_build_keys_per_s": 406000.0,
    "stage_ms": {"read": 1200.0, "pack": 400.0, "pull": 300.0,
                 "dispatch": 9000.0, "sync": 50.0},
    "boundary": {"end_ms": 900.0, "build_ms": 4000.0,
                 "feed_wait_ms": 1000.0, "overlap_frac": 0.75},
    "bottleneck": {"stage": "reader", "device_idle_frac": 0.4,
                   "host_critical_share": 0.6},
    "dispatch_ms_quantiles": {"p50": 120.0, "p90": 150.0, "p99": 300.0,
                              "p999": 800.0, "count": 64},
    "lookup_exchange_bytes": 19200,
    "auc": 0.78,
    "seg_cache_hit_rate": 0.9,
    "n_devices": 1,
    "steps_per_dispatch": 4,
    "sparse_gather_kernel": "auto",
}


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def test_gate_passes_on_baseline_identical_report(tmp_path, capsys):
    rep = _write(tmp_path, "rep.json", BASE)
    base = _write(tmp_path, "base.json", BASE)
    assert perf_gate.main([rep, "--baseline", base]) == 0
    out = capsys.readouterr().out
    assert "0 regression(s)" in out


def test_gate_fails_on_synthetic_regressions(tmp_path, capsys):
    bad = copy.deepcopy(BASE)
    bad["value"] *= 0.6                                  # throughput drop
    bad["stage_ms"]["read"] *= 5.0                       # stage blow-up
    bad["dispatch_ms_quantiles"]["p99"] = 3000.0         # tail explosion
    bad["bottleneck"]["device_idle_frac"] = 0.9          # starved device
    rep = _write(tmp_path, "rep.json", bad)
    base = _write(tmp_path, "base.json", BASE)
    assert perf_gate.main([rep, "--baseline", base]) == 1
    out = capsys.readouterr().out
    for name in ("value", "stage_ms.read", "dispatch_ms_quantiles.p99",
                 "bottleneck.device_idle_frac"):
        assert name in out, out


def test_gate_ignores_improvements_and_unknown_fields(tmp_path):
    good = copy.deepcopy(BASE)
    good["value"] *= 3.0
    good["stage_ms"]["read"] = 1.0
    good["e2e_over_device_only"] = 0.9
    good["n_devices"] = 8                 # count: not gated
    good["sparse_gather_kernel"] = "pallas"  # string: not gated
    good["brand_new_metric_per_s"] = 1.0  # absent from baseline: skipped
    rep = _write(tmp_path, "rep.json", good)
    base = _write(tmp_path, "base.json", BASE)
    assert perf_gate.main([rep, "--baseline", base]) == 0


def test_tolerances_default_and_per_metric(tmp_path):
    wob = copy.deepcopy(BASE)
    wob["value"] *= 0.9          # -10% < default 15% tolerance
    rep = _write(tmp_path, "rep.json", wob)
    base = _write(tmp_path, "base.json", BASE)
    assert perf_gate.main([rep, "--baseline", base]) == 0
    # Tighten the default: now it trips...
    assert perf_gate.main([rep, "--baseline", base,
                           "--tolerance", "0.05"]) == 1
    # ...unless a per-metric override loosens exactly that metric.
    assert perf_gate.main([rep, "--baseline", base,
                           "--tolerance", "0.05",
                           "--tol", "value=0.2"]) == 0


def test_abs_floor_suppresses_micro_ms_noise(tmp_path):
    wob = copy.deepcopy(BASE)
    wob["stage_ms"]["sync"] = 50.8   # +1.6% and +0.8ms: noise
    rep = _write(tmp_path, "rep.json", wob)
    base = _write(tmp_path, "base.json", BASE)
    assert perf_gate.main([rep, "--baseline", base,
                           "--tolerance", "0.0"]) == 0
    # But a genuine ms regression past both gates fails.
    wob["stage_ms"]["sync"] = 80.0
    rep = _write(tmp_path, "rep2.json", wob)
    assert perf_gate.main([rep, "--baseline", base]) == 1


def test_write_baseline_roundtrip(tmp_path):
    rep = _write(tmp_path, "rep.json", BASE)
    out = str(tmp_path / "new_base.json")
    assert perf_gate.main([rep, "--write-baseline", out]) == 0
    assert perf_gate.main([rep, "--baseline", out]) == 0


def test_builtin_smoke_self_check():
    assert perf_gate.smoke() == 0
    assert perf_gate.main(["--smoke"]) == 0


def test_gates_a_real_pass_report_shape(tmp_path):
    """End-to-end with the trainer's actual pass_report schema: gate a
    report against itself (0) and against a degraded twin (1). Uses a
    canned summary (the full-trainer path is covered by
    test_pipeline_stats) so this stays jax-free and milliseconds."""
    summary = {
        "kind": "train", "steps": 13, "samples": 416, "wall_s": 1.9,
        "samples_per_s": 221.8,
        "stage_ms": {"read": 14.6, "pack": 6.5, "pull": 0.7,
                     "fwd_bwd": 0.0, "push": 152.6, "dispatch": 1278.5,
                     "sync": 0.6},
        "bottleneck": {"stage": "device", "device_idle_frac": 0.05,
                       "host_critical_share": 0.2},
        "dispatch_ms_quantiles": {"p50": 95.0, "p99": 140.0,
                                  "count": 4},
    }
    base = _write(tmp_path, "base.json", summary)
    rep = _write(tmp_path, "rep.json", summary)
    assert perf_gate.main([rep, "--baseline", base]) == 0
    worse = copy.deepcopy(summary)
    worse["samples_per_s"] = 100.0
    worse["bottleneck"]["host_critical_share"] = 0.8
    rep2 = _write(tmp_path, "rep2.json", worse)
    assert perf_gate.main([rep2, "--baseline", base]) == 1


def test_gates_a_graftlint_summary(tmp_path):
    """The static-analysis trend wire: a graftlint --summary JSON gated
    against a recorded one fails when the finding/baseline/pragma
    surface grows (counts are lower-better via LOWER_NAMES), and passes
    when it shrinks."""
    summary = {
        "findings_total": 12, "new": 0, "baselined": 0, "allowed": 12,
        "warnings": 1, "files_scanned": 145,
        "per_pass": {"hot_sync": {"findings_total": 7, "new": 0,
                                  "baselined": 0, "allowed": 7}},
    }
    base = _write(tmp_path, "gl_base.json", summary)
    same = _write(tmp_path, "gl_same.json", summary)
    assert perf_gate.main([same, "--baseline", base]) == 0
    grown = copy.deepcopy(summary)
    grown["new"] = 3                      # a non-baselined finding
    grown["per_pass"]["hot_sync"]["new"] = 3
    rep = _write(tmp_path, "gl_grown.json", grown)
    assert perf_gate.main([rep, "--baseline", base]) == 1
    crept = copy.deepcopy(summary)
    crept["baselined"] = 9                # silent baseline growth
    rep2 = _write(tmp_path, "gl_crept.json", crept)
    assert perf_gate.main([rep2, "--baseline", base]) == 1
    shrunk = copy.deepcopy(summary)
    shrunk["findings_total"] = 4
    shrunk["allowed"] = 4
    rep3 = _write(tmp_path, "gl_shrunk.json", shrunk)
    assert perf_gate.main([rep3, "--baseline", base]) == 0


def test_ingest_keys_direction_and_gating(tmp_path):
    """Round-13 ingest/store-build keys: the throughput rates gate as
    higher-better, provenance fields (worker count, native bool) never
    gate, and a planted ingest regression fails a real report pair."""
    assert perf_gate.direction("ingest_rows_per_s") == 1
    assert perf_gate.direction("store_build_keys_per_s") == 1
    assert perf_gate.direction("host_index_build_keys_per_s") == 1
    assert perf_gate.direction("host_index_bulk_build_keys_per_s") == 1
    assert perf_gate.direction("ingest_workers") == 0
    base = {"value": 9000.0, "ingest_rows_per_s": 250000.0,
            "store_build_keys_per_s": 8.5e6, "ingest_workers": 8,
            "store_build_native": True}
    b = _write(tmp_path, "ing_base.json", base)
    ok = dict(base, ingest_workers=2, store_build_native=False)
    assert perf_gate.main([_write(tmp_path, "ing_ok.json", ok),
                           "--baseline", b]) == 0
    bad = dict(base, ingest_rows_per_s=60000.0)
    assert perf_gate.main([_write(tmp_path, "ing_bad.json", bad),
                           "--baseline", b]) == 1


def test_multihost_keys_direction_and_gating(tmp_path):
    """bench.py multihost keys: exchange rates gate higher-better (the
    `_per_s` suffix must win over the lower-better `_bytes`/`_s`
    suffixes inside the same segment), reshard_ms gates lower-better,
    and the moved-row count is provenance (never gated)."""
    base = {"metric": "multihost_2host_exchange_keys_per_sec",
            "value": 2.9e6,
            "hosts": 2,
            "wire": {"f32": {"cross_host_exchange_bytes_per_s": 2.4e8,
                             "exchange_keys_per_s": 2.9e6,
                             "pull_ms": 7.0, "push_ms": 6.6},
                     "int8": {"cross_host_exchange_bytes_per_s": 3.1e7,
                              "exchange_keys_per_s": 8.0e5}},
            "reshard_ms": 13.0,
            "reshard_rows_per_s": 7.6e5,
            "reshard_moved_rows": 10036,
            "reshard_minimal_frac": 1.0}
    assert perf_gate.direction(
        "wire.f32.cross_host_exchange_bytes_per_s") == 1
    assert perf_gate.direction("wire.int8.exchange_keys_per_s") == 1
    assert perf_gate.direction("reshard_ms") == -1
    assert perf_gate.direction("reshard_rows_per_s") == 1
    assert perf_gate.direction("reshard_moved_rows") == 0
    assert perf_gate.direction("wire.f32.pull_ms") == -1

    bad = copy.deepcopy(base)
    bad["wire"]["f32"]["cross_host_exchange_bytes_per_s"] *= 0.4
    bad["reshard_ms"] = 120.0
    bad["reshard_moved_rows"] = 1  # provenance swing: must not gate
    rep = _write(tmp_path, "mh_rep.json", bad)
    b = _write(tmp_path, "mh_base.json", base)
    assert perf_gate.main([rep, "--baseline", b]) == 1
    _, regs = perf_gate.compare(bad, base)
    names = {r["metric"] for r in regs}
    assert "wire.f32.cross_host_exchange_bytes_per_s" in names
    assert "reshard_ms" in names
    assert "reshard_moved_rows" not in names
    # An int8-wire throughput IMPROVEMENT never trips.
    good = copy.deepcopy(base)
    good["wire"]["int8"]["exchange_keys_per_s"] *= 3.0
    _, regs = perf_gate.compare(good, base)
    assert not regs


def test_overlap_and_bytes_per_pass_direction_and_gating(tmp_path):
    """r22 keys: exchange_overlap_frac gates higher-better (a boundary
    that stops hiding its exchange is a regression),
    cross_host_bytes_per_pass gates lower-better through the
    unit-in-the-middle `_bytes_` rule (the quantized wire exists to
    shrink it), and the busy/wait walls gate as ordinary `_ms`."""
    base = {"metric": "multihost_2host_exchange_keys_per_sec",
            "value": 2.9e6,
            "wire": {"f32": {"cross_host_bytes_per_pass": 3.4e6},
                     "int8": {"cross_host_bytes_per_pass": 1.6e6}},
            "overlap": {"exchange_overlap_frac": 0.97,
                        "exchange_busy_ms": 18.0,
                        "exchange_wait_ms": 0.1,
                        "overlap_round_ms": 26.0}}
    assert perf_gate.direction("overlap.exchange_overlap_frac") == 1
    assert perf_gate.direction("wire.f32.cross_host_bytes_per_pass") == -1
    assert perf_gate.direction("wire.int8.cross_host_bytes_per_pass") == -1
    assert perf_gate.direction("overlap.exchange_busy_ms") == -1
    assert perf_gate.direction("overlap.exchange_wait_ms") == -1

    bad = copy.deepcopy(base)
    bad["overlap"]["exchange_overlap_frac"] = 0.3   # un-hidden boundary
    bad["wire"]["int8"]["cross_host_bytes_per_pass"] = 3.3e6  # wire grew
    rep = _write(tmp_path, "ov_rep.json", bad)
    b = _write(tmp_path, "ov_base.json", base)
    assert perf_gate.main([rep, "--baseline", b]) == 1
    _, regs = perf_gate.compare(bad, base)
    names = {r["metric"] for r in regs}
    assert "overlap.exchange_overlap_frac" in names
    assert "wire.int8.cross_host_bytes_per_pass" in names
    # Byte SHRINK and overlap IMPROVEMENT never trip.
    good = copy.deepcopy(base)
    good["wire"]["f32"]["cross_host_bytes_per_pass"] *= 0.4
    good["overlap"]["exchange_overlap_frac"] = 1.0
    good["overlap"]["exchange_wait_ms"] = 0.0
    _, regs = perf_gate.compare(good, base)
    assert not regs


def test_replication_failover_keys_direction_and_gating(tmp_path):
    """Round-18 replicated-tier keys: failover_blip_ms (pull p99
    across a scripted primary kill) and repair_ms gate lower-better,
    journal_catchup_rows_per_s higher-better; the failed-pull count is
    a correctness assertion inside the bench, never a gated rate."""
    assert perf_gate.direction("failover_blip_ms") == -1
    assert perf_gate.direction("failover_pull_p50_ms") == -1
    assert perf_gate.direction("repair_ms") == -1
    assert perf_gate.direction("journal_catchup_rows_per_s") == 1
    assert perf_gate.direction("failover_failed_pulls") == 0
    base = {"value": 2.9e6,
            "failover_blip_ms": 420.0,
            "failover_pull_p50_ms": 90.0,
            "repair_ms": 120.0,
            "journal_catchup_rows_per_s": 1.7e6,
            "failover_failed_pulls": 0}
    b = _write(tmp_path, "fo_base.json", base)
    assert perf_gate.main([_write(tmp_path, "fo_same.json", base),
                           "--baseline", b]) == 0
    for key, val in (("failover_blip_ms", 5000.0),
                     ("repair_ms", 9000.0),
                     ("journal_catchup_rows_per_s", 2.0e5)):
        bad = copy.deepcopy(base)
        bad[key] = val
        assert perf_gate.main(
            [_write(tmp_path, f"fo_bad_{key}.json", bad),
             "--baseline", b]) == 1, key
    # A faster repair never trips.
    good = copy.deepcopy(base)
    good["repair_ms"] = 20.0
    good["failover_blip_ms"] = 50.0
    _, regs = perf_gate.compare(good, base)
    assert not regs


def test_serve_client_keys_direction_and_gating(tmp_path):
    """Round-14 serving keys: the concurrent-client wire-mode record
    (`bench.py serve --clients N`) gates throughput_rps / rows_per_s /
    batch_fill_frac as higher-better and the latency quantiles as
    lower-better; planted regressions on each fail a real report pair
    and provenance (client/request counts) never gates."""
    assert perf_gate.direction("clients.c32.throughput_rps") == 1
    assert perf_gate.direction("clients.c32.rows_per_s") == 1
    assert perf_gate.direction("clients.c32.batch_fill_frac") == 1
    assert perf_gate.direction("clients.c1.predict_p50_ms") == -1
    assert perf_gate.direction("clients.c32.predict_p99_ms") == -1
    assert perf_gate.direction("clients.c32.requests") == 0
    assert perf_gate.direction("clients.c32.batches") == 0
    base = {"value": 90000.0,
            "clients": {
                "c1": {"throughput_rps": 300.0, "rows_per_s": 19200.0,
                       "predict_p50_ms": 3.0, "predict_p99_ms": 6.0,
                       "batch_fill_frac": 0.12, "requests": 900,
                       "batches": 900},
                "c32": {"throughput_rps": 4500.0,
                        "rows_per_s": 288000.0,
                        "predict_p50_ms": 5.0, "predict_p99_ms": 11.0,
                        "batch_fill_frac": 0.85, "requests": 13500,
                        "batches": 600}}}
    b = _write(tmp_path, "srv_base.json", base)
    same = _write(tmp_path, "srv_same.json", base)
    assert perf_gate.main([same, "--baseline", b]) == 0
    # Provenance wobble (fewer requests completed in the window because
    # the box was busy) must not gate on its own.
    ok = copy.deepcopy(base)
    ok["clients"]["c32"]["requests"] = 9000
    ok["clients"]["c32"]["batches"] = 400
    assert perf_gate.main([_write(tmp_path, "srv_ok.json", ok),
                           "--baseline", b]) == 0
    for key, val in (("throughput_rps", 1500.0),
                     ("rows_per_s", 96000.0),
                     ("predict_p99_ms", 40.0),
                     ("batch_fill_frac", 0.3)):
        bad = copy.deepcopy(base)
        bad["clients"]["c32"][key] = val
        assert perf_gate.main(
            [_write(tmp_path, f"srv_bad_{key}.json", bad),
             "--baseline", b]) == 1, key


def test_fleet_replica_keys_direction_and_gating(tmp_path):
    """Round-16 fleet keys: the `bench.py serve --replicas R` record
    gates aggregate throughput_rps / rows_per_s / batch_fill_frac as
    higher-better, router route_ms quantiles and the degraded-path
    share as lower-better; client/request counts are provenance and
    never gate."""
    assert perf_gate.direction("replicas.r2.throughput_rps") == 1
    assert perf_gate.direction("replicas.r2.rows_per_s") == 1
    assert perf_gate.direction("replicas.r2.batch_fill_frac") == 1
    assert perf_gate.direction("replicas.r2.route_ms_quantiles.p50") == -1
    assert perf_gate.direction("replicas.r2.route_ms_quantiles.p99") == -1
    assert perf_gate.direction("replicas.r2.degraded_frac") == -1
    assert perf_gate.direction("replicas.r2.clients") == 0
    assert perf_gate.direction("replicas.r2.requests") == 0
    base = {"value": 90000.0,
            "replicas": {
                "r1": {"throughput_rps": 4200.0, "rows_per_s": 268800.0,
                       "route_ms_quantiles": {"p50": 1.2, "p99": 6.0},
                       "batch_fill_frac": 0.8, "degraded_frac": 0.0,
                       "clients": 4, "requests": 12600},
                "r2": {"throughput_rps": 7800.0, "rows_per_s": 499200.0,
                       "route_ms_quantiles": {"p50": 1.4, "p99": 7.0},
                       "batch_fill_frac": 0.75, "degraded_frac": 0.0,
                       "clients": 8, "requests": 23400}}}
    b = _write(tmp_path, "fleet_base.json", base)
    assert perf_gate.main(
        [_write(tmp_path, "fleet_same.json", base),
         "--baseline", b]) == 0
    # Provenance wobble (window completed fewer requests) never gates.
    ok = copy.deepcopy(base)
    ok["replicas"]["r2"]["requests"] = 11000
    ok["replicas"]["r2"]["clients"] = 6
    assert perf_gate.main([_write(tmp_path, "fleet_ok.json", ok),
                           "--baseline", b]) == 0
    for key, val in (("throughput_rps", 2000.0),
                     ("rows_per_s", 120000.0),
                     ("batch_fill_frac", 0.2),
                     ("degraded_frac", 0.4)):
        bad = copy.deepcopy(base)
        bad["replicas"]["r2"][key] = val
        assert perf_gate.main(
            [_write(tmp_path, f"fleet_bad_{key}.json", bad),
             "--baseline", b]) == 1, key
    bad = copy.deepcopy(base)
    bad["replicas"]["r2"]["route_ms_quantiles"]["p99"] = 60.0
    assert perf_gate.main(
        [_write(tmp_path, "fleet_bad_route.json", bad),
         "--baseline", b]) == 1


def test_online_freshness_direction_and_gating(tmp_path):
    """Round-17 streaming keys: the `bench.py online` record gates the
    event→servable freshness quantiles as lower-better (a staler
    served model is a regression), passes_per_hour as higher-better,
    and the post-lifecycle store row count as lower-better (TTL/decay
    stopped bounding the table); pass/event totals are workload
    provenance and never gate."""
    assert perf_gate.direction("event_to_servable_ms.p50") == -1
    assert perf_gate.direction("event_to_servable_ms.p99") == -1
    assert perf_gate.direction("passes_per_hour") == 1
    assert perf_gate.direction("post_shrink_store_rows") == -1
    assert perf_gate.direction("stream_passes") == 0
    assert perf_gate.direction("events") == 0
    assert perf_gate.direction("day3_over_day1_rows") == 0
    base = {"metric": "online_stream_events_per_sec", "value": 2900.0,
            "event_to_servable_ms": {"p50": 900.0, "p99": 2500.0},
            "passes_per_hour": 620.0,
            "post_shrink_store_rows": 31000,
            "day3_over_day1_rows": 1.01,
            "stream_passes": 12, "events": 49152}
    b = _write(tmp_path, "online_base.json", base)
    assert perf_gate.main(
        [_write(tmp_path, "online_same.json", base),
         "--baseline", b]) == 0
    # Provenance wobble (a different carve) never gates.
    ok = copy.deepcopy(base)
    ok["stream_passes"] = 6
    ok["events"] = 24000
    assert perf_gate.main([_write(tmp_path, "online_ok.json", ok),
                           "--baseline", b]) == 0
    # Freshness blown: the p99 event→servable latency trips the gate.
    bad = copy.deepcopy(base)
    bad["event_to_servable_ms"]["p99"] = 60000.0
    assert perf_gate.main(
        [_write(tmp_path, "online_bad_fresh.json", bad),
         "--baseline", b]) == 1
    # Lifecycle broken: an unbounded post-shrink store trips it too.
    bad = copy.deepcopy(base)
    bad["post_shrink_store_rows"] = 500000
    assert perf_gate.main(
        [_write(tmp_path, "online_bad_rows.json", bad),
         "--baseline", b]) == 1
    bad = copy.deepcopy(base)
    bad["passes_per_hour"] = 80.0
    assert perf_gate.main(
        [_write(tmp_path, "online_bad_pph.json", bad),
         "--baseline", b]) == 1


def test_telemetry_overhead_direction_and_gating(tmp_path):
    """Round-19 distributed-tracing keys: the bench serve/multihost
    `telemetry` record gates the off-vs-on overhead fraction as
    lower-better (tracing that gets expensive gets turned off exactly
    when it is needed) and the absolute off/on rates as higher-better;
    the scrape count is workload provenance and never gates."""
    assert perf_gate.direction(
        "telemetry.telemetry_overhead_frac") == -1
    assert perf_gate.direction("telemetry.trace_off_rps") == 1
    assert perf_gate.direction("telemetry.trace_on_rps") == 1
    assert perf_gate.direction(
        "telemetry.trace_on_keys_per_s") == 1
    assert perf_gate.direction("telemetry.scrapes") == 0
    base = {"metric": "serve_clients_rps", "value": 1900.0,
            "telemetry": {"telemetry_overhead_frac": 0.02,
                          "trace_off_rps": 1900.0,
                          "trace_on_rps": 1860.0,
                          "scrapes": 40}}
    b = _write(tmp_path, "tel_base.json", base)
    assert perf_gate.main(
        [_write(tmp_path, "tel_same.json", base), "--baseline", b]) == 0
    # Fewer scrapes (a shorter window) never gates.
    ok = copy.deepcopy(base)
    ok["telemetry"]["scrapes"] = 4
    assert perf_gate.main(
        [_write(tmp_path, "tel_ok.json", ok), "--baseline", b]) == 0
    # Tracing got expensive: the overhead fraction trips the gate.
    bad = copy.deepcopy(base)
    bad["telemetry"]["telemetry_overhead_frac"] = 0.4
    bad["telemetry"]["trace_on_rps"] = 1150.0
    assert perf_gate.main(
        [_write(tmp_path, "tel_bad.json", bad), "--baseline", b]) == 1


def test_quality_keys_direction_and_gating(tmp_path):
    """Round-20 model-quality keys: the `bench.py online` quality block
    gates calibration_error (and its quantile leaves) lower-better,
    alarm counts lower-better, slot coverage higher-better; COPC
    (target 1.0, not monotonic-better in either direction) and the
    skew/churn data-shape numbers are provenance and never gate."""
    assert perf_gate.direction("quality.calibration_error") == -1
    assert perf_gate.direction("quality.calibration_error.p99") == -1
    assert perf_gate.direction("quality.quality_alarms") == -1
    assert perf_gate.direction("quality.slot_coverage") == 1
    assert perf_gate.direction("quality.copc") == 0
    assert perf_gate.direction("quality.skew_top_share") == 0
    assert perf_gate.direction("quality.key_churn") == 0
    base = {"metric": "online_stream_events_per_sec", "value": 2900.0,
            "quality": {"copc": 1.0,
                        "calibration_error": {"p99": 0.05},
                        "quality_alarms": 0,
                        "slot_coverage": 0.99,
                        "skew_top_share": 0.35,
                        "key_churn": 0.5}}
    b = _write(tmp_path, "q_base.json", base)
    assert perf_gate.main(
        [_write(tmp_path, "q_same.json", base), "--baseline", b]) == 0
    # Data-shape wobble (different traffic mix) never gates — and a
    # COPC move is a quality ALARM's job, not the perf gate's.
    ok = copy.deepcopy(base)
    ok["quality"]["copc"] = 0.6
    ok["quality"]["skew_top_share"] = 0.9
    ok["quality"]["key_churn"] = 0.9
    assert perf_gate.main(
        [_write(tmp_path, "q_ok.json", ok), "--baseline", b]) == 0
    # Calibration blown: the error p99 trips the gate.
    bad = copy.deepcopy(base)
    bad["quality"]["calibration_error"]["p99"] = 0.5
    assert perf_gate.main(
        [_write(tmp_path, "q_bad_cal.json", bad), "--baseline", b]) == 1
    # Drift alarms fired on an identical workload: trips it too.
    bad = copy.deepcopy(base)
    bad["quality"]["quality_alarms"] = 7
    assert perf_gate.main(
        [_write(tmp_path, "q_bad_alarm.json", bad),
         "--baseline", b]) == 1
    # A slot going dark (coverage collapse) trips it.
    bad = copy.deepcopy(base)
    bad["quality"]["slot_coverage"] = 0.2
    assert perf_gate.main(
        [_write(tmp_path, "q_bad_cov.json", bad), "--baseline", b]) == 1


def test_rpc_keys_direction_and_gating(tmp_path):
    """bench.py rpc keys (PR 16 event-loop/mux wire): per-cell
    calls_per_s and bytes_per_s gate higher-better (`_per_s` wins over
    the lower-better `_bytes` suffix in the same segment), the window
    p50/p99 gate lower-better, and the mux-over-legacy ratio + frame
    counts are provenance (never gated)."""
    base = {"metric": "rpc_echo_mux_calls_per_sec",
            "value": 30000.0,
            "windows": 400,
            "mux_over_legacy_at_o4": 2.6,
            "sg_frames": 842,
            "modes": {"legacy": {"64b_o4": {"calls_per_s": 11000.0,
                                            "p50_ms": 0.35,
                                            "p99_ms": 1.0,
                                            "bytes_per_s": 1.4e6}},
                      "mux": {"64b_o4": {"calls_per_s": 30000.0,
                                         "p50_ms": 0.13,
                                         "p99_ms": 0.5,
                                         "bytes_per_s": 3.8e6}},
                      "sg": {"1mb_o4": {"calls_per_s": 1800.0,
                                        "p50_ms": 2.2,
                                        "p99_ms": 6.0,
                                        "bytes_per_s": 3.8e9}}}}
    assert perf_gate.direction("modes.mux.64b_o4.calls_per_s") == 1
    assert perf_gate.direction("modes.sg.1mb_o4.bytes_per_s") == 1
    assert perf_gate.direction("modes.mux.64b_o4.p99_ms") == -1
    assert perf_gate.direction("mux_over_legacy_at_o4") == 0
    assert perf_gate.direction("sg_frames") == 0
    assert perf_gate.direction("windows") == 0

    b = _write(tmp_path, "rpc_base.json", base)
    assert perf_gate.main(
        [_write(tmp_path, "rpc_same.json", base), "--baseline", b]) == 0
    # Mux throughput collapse and a blown tail each trip the gate.
    bad = copy.deepcopy(base)
    bad["modes"]["mux"]["64b_o4"]["calls_per_s"] *= 0.4
    bad["modes"]["sg"]["1mb_o4"]["p99_ms"] = 80.0
    rep = _write(tmp_path, "rpc_bad.json", bad)
    assert perf_gate.main([rep, "--baseline", b]) == 1
    _, regs = perf_gate.compare(bad, base)
    names = {r["metric"] for r in regs}
    assert "modes.mux.64b_o4.calls_per_s" in names
    assert "modes.sg.1mb_o4.p99_ms" in names
    # The speedup ratio drifting is provenance, never a gate trip.
    ok = copy.deepcopy(base)
    ok["mux_over_legacy_at_o4"] = 0.5
    ok["sg_frames"] = 3
    assert perf_gate.main(
        [_write(tmp_path, "rpc_ok.json", ok), "--baseline", b]) == 0


def test_health_plane_keys_direction_and_gating(tmp_path):
    """Round-18 fleet-health keys: the history-sampler overhead
    fraction gates lower-better like the tracing overhead, and
    ``alerts_firing`` gates lower-better FROM A ZERO BASELINE (the
    counter floor makes 0→any rise a trip — a healthy bench must end
    with nothing firing)."""
    assert perf_gate.direction("telemetry.history_overhead_frac") == -1
    assert perf_gate.direction("telemetry.alerts_firing") == -1
    assert perf_gate.direction("telemetry.history_on_rps") == 1
    base = {"value": 9000.0,
            "telemetry": {"telemetry_overhead_frac": 0.02,
                          "history_on_rps": 1850.0,
                          "history_overhead_frac": 0.03,
                          "alerts_firing": 0}}
    b = _write(tmp_path, "hp_base.json", base)
    assert perf_gate.main([_write(tmp_path, "hp_ok.json", base),
                           "--baseline", b]) == 0
    costly = copy.deepcopy(base)
    costly["telemetry"]["history_overhead_frac"] = 0.5
    assert perf_gate.main([_write(tmp_path, "hp_costly.json", costly),
                           "--baseline", b]) == 1
    firing = copy.deepcopy(base)
    firing["telemetry"]["alerts_firing"] = 2
    rep = _write(tmp_path, "hp_firing.json", firing)
    assert perf_gate.main([rep, "--baseline", b]) == 1
    _, regs = perf_gate.compare(firing, base)
    assert {r["metric"] for r in regs} == {"telemetry.alerts_firing"}


def test_hbm_residency_keys_direction_and_gating(tmp_path):
    """ZeRO/slot-offload keys: measured HBM residency gates lower-better
    through the "_bytes" suffix (slash-separated names are one path
    segment — the suffix rule still sees them), and the placement
    strings (``dense_zero``, ``table_slot_placement``) are provenance
    that must never gate. Shrinking resident bytes (turning ZeRO on
    against an off baseline) is an improvement, never a trip."""
    assert perf_gate.direction("dense/opt_state_hbm_bytes") == -1
    assert perf_gate.direction("dense/params_hbm_bytes") == -1
    assert perf_gate.direction("table/slot_hbm_bytes") == -1
    assert perf_gate.direction("table/hot_hbm_bytes") == -1
    base = {"value": 8500.0,
            "dense/params_hbm_bytes": 1972808,
            "dense/opt_state_hbm_bytes": 3945620,
            "table/hot_hbm_bytes": 79691852,
            "table/slot_hbm_bytes": 8388616,
            "dense_zero": "shard",
            "table_slot_placement": "host"}
    b = _write(tmp_path, "hbm_base.json", base)
    assert perf_gate.main([_write(tmp_path, "hbm_ok.json", base),
                           "--baseline", b]) == 0
    # Optimizer state grew back to replicated size: a memory regression
    # even with throughput flat.
    grew = copy.deepcopy(base)
    grew["dense/opt_state_hbm_bytes"] *= 2
    grew["dense_zero"] = "off"  # provenance flip rides along, ungated
    assert perf_gate.main([_write(tmp_path, "hbm_grew.json", grew),
                           "--baseline", b]) == 1
    _, regs = perf_gate.compare(grew, base)
    assert {r["metric"] for r in regs} == {"dense/opt_state_hbm_bytes"}
    # Slot columns crept back into HBM (placement silently fused).
    crept = copy.deepcopy(base)
    crept["table/slot_hbm_bytes"] *= 5
    _, regs = perf_gate.compare(crept, base)
    assert {r["metric"] for r in regs} == {"table/slot_hbm_bytes"}
    # Turning the features ON against an off baseline only shrinks
    # bytes: an improvement must not trip the gate.
    shrunk = copy.deepcopy(base)
    shrunk["dense/opt_state_hbm_bytes"] //= 2
    shrunk["table/slot_hbm_bytes"] = 0
    _, regs = perf_gate.compare(shrunk, base)
    assert regs == []


def test_autopilot_soak_keys_direction_and_gating(tmp_path):
    """Round-20 chaos-soak keys: a dropped client RPC
    (``soak.failed_rpcs``, exact-name lower-better — the drill's
    baseline is ZERO so any drop trips) and the soaked predict tail
    (``soak.predict_p99_ms`` via the ``_ms`` suffix) gate the bench;
    the ACTION counts (``scale_actions``, ``canary_blocked``) are
    chaos-script provenance — how much healing the script demanded —
    and must never gate in either direction."""
    assert perf_gate.direction("soak.failed_rpcs") == -1
    assert perf_gate.direction("soak.predict_p99_ms") == -1
    assert perf_gate.direction("soak.degraded_frac") == -1
    assert perf_gate.direction("soak.scale_actions") == 0
    assert perf_gate.direction("soak.canary_blocked") == 0
    base = {"value": 9100.0,
            "soak": {"failed_rpcs": 0, "predict_p99_ms": 14.0,
                     "degraded_frac": 0.0, "scale_actions": 2,
                     "canary_blocked": 1}}
    b = _write(tmp_path, "soak_base.json", base)
    assert perf_gate.main([_write(tmp_path, "soak_ok.json", base),
                           "--baseline", b]) == 0
    # One dropped RPC under chaos is a robustness regression outright.
    dropped = copy.deepcopy(base)
    dropped["soak"]["failed_rpcs"] = 1
    assert perf_gate.main([_write(tmp_path, "soak_drop.json", dropped),
                           "--baseline", b]) == 1
    _, regs = perf_gate.compare(dropped, base)
    assert {r["metric"] for r in regs} == {"soak.failed_rpcs"}
    # The soaked tail blowing out gates even with zero failures.
    slow = copy.deepcopy(base)
    slow["soak"]["predict_p99_ms"] = 400.0
    _, regs = perf_gate.compare(slow, base)
    assert {r["metric"] for r in regs} == {"soak.predict_p99_ms"}
    # A different chaos script (more kills → more heals, a canary that
    # promoted instead of blocking) is provenance, never a trip.
    other = copy.deepcopy(base)
    other["soak"]["scale_actions"] = 9
    other["soak"]["canary_blocked"] = 0
    _, regs = perf_gate.compare(other, base)
    assert regs == []
