"""1F1B pipeline schedule: gradient parity with the GPipe-autodiff path
and the bounded-activation-memory property that motivates it.

Role of the reference 1F1B (meta_parallel/pipeline_parallel.py:82,
section_worker.cc:40-63).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.parallel.pp import (gpipe_apply,
                                       one_f_one_b_value_and_grad,
                                       stack_stage_params)

N_STAGES = 4
F = 8


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _loss_fn(y, t):
    return jnp.mean((y - t) ** 2)


def _setup(m, mb=4, seed=0):
    rng = np.random.default_rng(seed)
    stages = [{"w": jnp.asarray(rng.normal(0, 0.5, (F, F)), jnp.float32),
               "b": jnp.asarray(rng.normal(0, 0.1, (F,)), jnp.float32)}
              for _ in range(N_STAGES)]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.normal(size=(m, mb, F)), jnp.float32)
    t = jnp.asarray(rng.normal(size=(m, mb, F)), jnp.float32)
    return stacked, x, t


def _mesh():
    return build_mesh(HybridTopology(pp=N_STAGES),
                      devices=jax.devices()[:N_STAGES])


def _gpipe_loss_fn(mesh):
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P(), P()), out_specs=P(), check_vma=False)
    def run(stacked, x_mb, t_mb):
        params_local = jax.tree.map(lambda a: a[0], stacked)
        out = gpipe_apply(_stage_fn, params_local, x_mb, axis="pp")
        return jax.vmap(_loss_fn)(out, t_mb).mean()

    return lambda stacked, x, t: run(stacked, x, t)[()]


def _f1b_fn(mesh):
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P("pp")), check_vma=False)
    def run(stacked, x_mb, t_mb):
        params_local = jax.tree.map(lambda a: a[0], stacked)
        loss, grads = one_f_one_b_value_and_grad(
            _stage_fn, _loss_fn, params_local, x_mb, t_mb, axis="pp")
        return loss, jax.tree.map(lambda g: g[None], grads)

    return run


def test_1f1b_matches_gpipe_autodiff():
    mesh = _mesh()
    stacked, x, t = _setup(m=8)
    ref_loss_fn = _gpipe_loss_fn(mesh)
    ref_loss, ref_grads = jax.value_and_grad(ref_loss_fn)(stacked, x, t)
    loss, grads = jax.jit(_f1b_fn(mesh))(stacked, x, t)
    assert np.isclose(float(loss), float(ref_loss), rtol=1e-5), (
        float(loss), float(ref_loss))
    for a, b in zip(jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(ref_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_1f1b_activation_memory_independent_of_microbatches():
    """GPipe-through-autodiff stashes O(M) residuals; 1F1B's carry is a
    fixed 2n-1 ring. Compare compiled temp memory growth as M scales
    8 -> 64: the 1F1B growth must be a small fraction of GPipe's."""
    mesh = _mesh()

    def temp_bytes(fn, *args):
        lowered = jax.jit(fn).lower(*args)
        mem = lowered.compile().memory_analysis()
        if mem is None:
            pytest.skip("backend exposes no memory analysis")
        return mem.temp_size_in_bytes

    sizes = {}
    for m in (8, 64):
        stacked, x, t = _setup(m=m)
        ref = _gpipe_loss_fn(mesh)
        sizes[("gpipe", m)] = temp_bytes(
            lambda s, xx, tt: jax.value_and_grad(ref)(s, xx, tt),
            stacked, x, t)
        sizes[("1f1b", m)] = temp_bytes(_f1b_fn(mesh), stacked, x, t)

    gpipe_growth = sizes[("gpipe", 64)] - sizes[("gpipe", 8)]
    f1b_growth = sizes[("1f1b", 64)] - sizes[("1f1b", 8)]
    # 8x more microbatches: GPipe temp grows ~linearly (activation
    # stash); the 1F1B ring is fixed-size so its growth (scan inputs,
    # streamed microbatch buffers) must be far smaller.
    assert f1b_growth < gpipe_growth / 2, sizes
    assert sizes[("1f1b", 64)] < sizes[("gpipe", 64)], sizes


def test_1f1b_with_head_params_and_embedding_grads():
    """Full-model composition: embedding OUTSIDE the pipeline (grads via
    returned input cotangents), head/readout params differentiated at the
    last stage (loss_params). Parity vs straight autodiff through the
    GPipe path."""
    mesh = _mesh()
    m, mb = 8, 4
    rng = np.random.default_rng(1)
    stages = [{"w": jnp.asarray(rng.normal(0, 0.5, (F, F)), jnp.float32),
               "b": jnp.asarray(rng.normal(0, 0.1, (F,)), jnp.float32)}
              for _ in range(N_STAGES)]
    stacked = stack_stage_params(stages)
    embed = jnp.asarray(rng.normal(0, 0.5, (16, F)), jnp.float32)
    head = {"v": jnp.asarray(rng.normal(0, 0.5, (F,)), jnp.float32)}
    tokens = jnp.asarray(rng.integers(0, 16, (m, mb)), jnp.int32)
    t = jnp.asarray(rng.normal(size=(m, mb)), jnp.float32)

    def head_loss(lp, y, tgt):
        return jnp.mean((y @ lp["v"] - tgt) ** 2)

    # Reference: differentiate through the gpipe forward end-to-end.
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P(), P(), P(), P()), out_specs=P(),
        check_vma=False)
    def ref_loss_sm(stacked, embed, head, tokens, tgt):
        params_local = jax.tree.map(lambda a: a[0], stacked)
        x_mb = embed[tokens]                       # [m, mb, F]
        out = gpipe_apply(_stage_fn, params_local, x_mb, axis="pp")
        return jax.vmap(lambda y, tg: head_loss(head, y, tg))(
            out, tgt).mean()

    ref_loss, ref_grads = jax.value_and_grad(
        lambda s, e, h: ref_loss_sm(s, e, h, tokens, t)[()],
        argnums=(0, 1, 2))(stacked, embed, head)

    # 1F1B: embedding outside, head as loss_params, dx0 -> embed grads.
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P(), P(), P(), P()),
        out_specs=(P(), P("pp"), P(), P()), check_vma=False)
    def f1b_sm(stacked, embed, head, tokens, tgt):
        params_local = jax.tree.map(lambda a: a[0], stacked)
        x_mb = embed[tokens]
        loss, sg, hg, dx0 = one_f_one_b_value_and_grad(
            _stage_fn, head_loss, params_local, x_mb, tgt, axis="pp",
            loss_params=head, return_input_grads=True)
        # head grads live on the last stage only; input grads on rank 0.
        hg = jax.tree.map(lambda g: jax.lax.psum(g, "pp"), hg)
        dx0 = jax.lax.psum(dx0, "pp")
        return loss, jax.tree.map(lambda g: g[None], sg), hg, dx0

    loss, sg, hg, dx0 = jax.jit(f1b_sm)(stacked, embed, head, tokens, t)
    # Embedding grads: vjp of the (differentiable) embed lookup.
    _, emb_vjp = jax.vjp(lambda e: e[tokens], embed)
    (eg,) = emb_vjp(dx0)

    assert np.isclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves((sg, eg, hg)),
                    jax.tree_util.tree_leaves(ref_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
