"""Metric registry + fused-op variant tests (numpy-parity style, role of
the reference OpTest harness, SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.metrics import (BucketAucCalculator, ContinueCalculator,
                                   MetricRegistry, auc_accumulate,
                                   auc_compute, auc_state_init, parse_group)
from paddlebox_tpu.ops import (fused_concat, fused_seqpool_cvm,
                               fused_seqpool_cvm_full,
                               fused_seqpool_cvm_tradew,
                               fused_seqpool_cvm_with_conv,
                               fused_seqpool_cvm_with_credit,
                               fused_seqpool_cvm_with_diff_thres,
                               fused_seqpool_cvm_with_pcoc,
                               fusion_seqpool_cvm_concat, quantize,
                               rank_attention, rank_attention2)


def _auc_ref(preds, labels):
    order = np.argsort(preds, kind="stable")
    ranks = np.empty(len(preds))
    ranks[order] = np.arange(1, len(preds) + 1)
    pos = labels > 0.5
    npos, nneg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - npos * (npos + 1) / 2) / (npos * nneg)


# --- registry ---------------------------------------------------------------

def _rand_batch(rng, n=512):
    preds = rng.random(n).astype(np.float64)
    labels = (rng.random(n) < preds).astype(np.float64)  # informative preds
    return preds, labels


def test_bucket_auc_calculator_matches_exact():
    rng = np.random.default_rng(0)
    preds, labels = _rand_batch(rng)
    cal = BucketAucCalculator(1 << 14)
    cal.add_data(preds[:300], labels[:300])
    cal.add_data(preds[300:], labels[300:])
    out = cal.compute()
    assert abs(out["auc"] - _auc_ref(preds, labels)) < 1e-3
    np.testing.assert_allclose(out["mae"], np.abs(preds - labels).mean(),
                               rtol=1e-9)
    np.testing.assert_allclose(out["actual_ctr"], labels.mean(), rtol=1e-9)
    assert out["count"] == 512
    # reset happens in registry path; direct compute leaves state
    assert out["bucket_error"] >= 0.0


def test_registry_basic_and_phase_gating():
    reg = MetricRegistry()
    reg.init_metric("join_auc", "auc", phase=0, bucket_size=1 << 12)
    reg.init_metric("update_auc", "auc", phase=1, bucket_size=1 << 12)
    rng = np.random.default_rng(1)
    preds, labels = _rand_batch(rng)
    reg.phase = 0
    reg.add_data("join_auc", preds, labels)
    reg.add_data("update_auc", preds, labels)   # inactive: dropped
    assert reg.get_metric("join_auc")["count"] == 512
    assert reg.get_metric("update_auc")["count"] == 0
    # get_metric resets
    assert reg.get_metric("join_auc")["count"] == 0


def test_registry_mask_kind():
    reg = MetricRegistry()
    reg.init_metric("m", "mask", bucket_size=1 << 12)
    rng = np.random.default_rng(2)
    preds, labels = _rand_batch(rng)
    mask = rng.integers(0, 2, preds.shape[0])
    reg.add_data("m", preds, labels, mask=mask)
    out = reg.get_metric("m")
    keep = mask.astype(bool)
    assert out["count"] == keep.sum()
    assert abs(out["auc"] - _auc_ref(preds[keep], labels[keep])) < 5e-3


def test_registry_cmatch_rank_filtering():
    reg = MetricRegistry()
    reg.init_metric("c", "cmatch_rank", cmatch_rank_group="3 7",
                    ignore_rank=True, bucket_size=1 << 12)
    rng = np.random.default_rng(3)
    preds, labels = _rand_batch(rng)
    cmatch = rng.choice([3, 5, 7], preds.shape[0]).astype(np.uint64)
    reg.add_data("c", preds, labels, cmatch_rank=cmatch)
    keep = (cmatch == 3) | (cmatch == 7)
    out = reg.get_metric("c")
    assert out["count"] == keep.sum()
    assert abs(out["auc"] - _auc_ref(preds[keep], labels[keep])) < 5e-3


def test_registry_cmatch_rank_with_rank_bits():
    # high 32 bits cmatch, low 8 bits rank
    reg = MetricRegistry()
    reg.init_metric("cr", "cmatch_rank", cmatch_rank_group="2_1",
                    ignore_rank=False, bucket_size=1 << 12)
    tags = np.array([(2 << 32) | 1, (2 << 32) | 0, (3 << 32) | 1],
                    np.uint64)
    reg.add_data("cr", np.array([0.9, 0.8, 0.7]), np.array([1.0, 0.0, 1.0]),
                 cmatch_rank=tags)
    assert reg.get_metric("cr")["count"] == 1


def test_registry_multi_task_selects_column():
    reg = MetricRegistry()
    reg.init_metric("mt", "multi_task", cmatch_rank_group="0 1",
                    ignore_rank=True, bucket_size=1 << 12)
    rng = np.random.default_rng(4)
    n = 256
    preds = rng.random((2, n))
    labels = (rng.random(n) < 0.5).astype(np.float64)
    task = rng.integers(0, 2, n).astype(np.uint64)
    reg.add_data("mt", preds, labels, cmatch_rank=task)
    out = reg.get_metric("mt")
    assert out["count"] == n
    chosen = preds[task.astype(int), np.arange(n)]
    assert abs(out["auc"] - _auc_ref(chosen, labels)) < 5e-3


def test_registry_wuauc():
    reg = MetricRegistry()
    reg.init_metric("w", "wuauc", bucket_size=1 << 12)
    rng = np.random.default_rng(5)
    preds, labels = _rand_batch(rng, 400)
    uids = rng.integers(0, 20, 400)
    reg.add_data("w", preds, labels, uids=uids)
    out = reg.get_metric("w")
    assert 0.4 < out["wuauc"] <= 1.0
    assert out["wuauc_users"] > 0


def test_continue_calculator():
    cal = ContinueCalculator(num_buckets=4, max_value=2.0)
    preds = np.array([0.5, 1.5, 1.9, 0.1])
    labels = np.array([0.4, 1.6, 1.8, 0.0])
    cal.add_data(preds, labels)
    out = cal.compute()
    np.testing.assert_allclose(out["mae"], np.abs(preds - labels).mean(),
                               rtol=1e-9)
    assert out["count"] == 4
    assert len(out["bucket_mae"]) == 4
    # labels 0.4->bucket 0, 1.6/1.8 -> bucket 3, 0.0 -> bucket 0
    assert out["bucket_count"][0] == 2 and out["bucket_count"][3] == 2


def test_registry_reduce_fn_distributed_sum():
    """Two 'ranks' compute locally; allreduce by summing tables equals the
    single-rank result (the metrics.cc:286 contract)."""
    rng = np.random.default_rng(6)
    preds, labels = _rand_batch(rng)
    c_all = BucketAucCalculator(1 << 12)
    c_all.add_data(preds, labels)
    c0 = BucketAucCalculator(1 << 12)
    c1 = BucketAucCalculator(1 << 12)
    c0.add_data(preds[:256], labels[:256])
    c1.add_data(preds[256:], labels[256:])

    peers = {id(c0): c1, id(c1): c0}

    def make_reduce(me, other):
        state = {"i": 0}
        other_payloads = [other._table,
                          np.array([other._abserr, other._sqrerr,
                                    other._pred_sum, other._label_sum,
                                    other._count])]

        def rf(arr):
            out = arr + other_payloads[state["i"]]
            state["i"] += 1
            return out
        return rf

    out0 = c0.compute(make_reduce(c0, c1))
    ref = c_all.compute()
    np.testing.assert_allclose(out0["auc"], ref["auc"], rtol=1e-12)
    np.testing.assert_allclose(out0["mae"], ref["mae"], rtol=1e-12)


def test_device_auc_includes_bucket_error():
    state = auc_state_init(1 << 10)
    rng = np.random.default_rng(7)
    preds, labels = _rand_batch(rng)
    state = auc_accumulate(state, jnp.asarray(preds, jnp.float32),
                           jnp.asarray(labels, jnp.float32))
    out = auc_compute(state)
    assert "bucket_error" in out and out["bucket_error"] >= 0.0


def test_parse_group():
    assert parse_group("3 7", True) == ((3, 0), (7, 0))
    assert parse_group("2_1 4_0", False) == ((2, 1), (4, 0))


# --- fused op variants ------------------------------------------------------

def _csr(rng, n_rows, cols, max_len=3):
    lens = rng.integers(0, max_len + 1, n_rows)
    n = int(lens.sum())
    segs = np.repeat(np.arange(n_rows), lens).astype(np.int32)
    x = rng.random((n, cols)).astype(np.float32) * 3
    return x, segs, lens


def test_fused_seqpool_cvm_full_filter_and_quant():
    rng = np.random.default_rng(8)
    d = 4
    x, segs, lens = _csr(rng, 6, 2 + d)
    out = fused_seqpool_cvm_full(
        jnp.asarray(x), jnp.asarray(segs), 6, need_filter=True,
        show_coeff=0.2, clk_coeff=1.0, threshold=0.96, quant_ratio=128)
    # numpy reference
    ref = np.zeros((6, 2 + d))
    for i in range(x.shape[0]):
        r = segs[i]
        show, click = x[i, 0], x[i, 1]
        if (show - click) * 0.2 + click * 1.0 < 0.96:
            continue
        ref[r, :2] += x[i, :2]
        ref[r, 2:] += np.trunc(x[i, 2:] * 128 + 0.5) / 128
    expect = np.concatenate([
        np.log(ref[:, :1] + 1),
        np.log(ref[:, 1:2] + 1) - np.log(ref[:, :1] + 1),
        ref[:, 2:]], axis=1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-6)


def test_fused_seqpool_cvm_with_conv_modes():
    rng = np.random.default_rng(9)
    d = 3
    x, segs, _ = _csr(rng, 5, 3 + d)
    pooled = np.zeros((5, 3 + d))
    np.add.at(pooled, segs, x)
    out = fused_seqpool_cvm_with_conv(jnp.asarray(x), jnp.asarray(segs), 5)
    expect = np.concatenate([
        np.log(pooled[:, :1] + 1),
        np.log(pooled[:, 1:2] + 1),
        np.log(pooled[:, 2:3] + 1) - np.log(pooled[:, 1:2] + 1),
        pooled[:, 3:]], axis=1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-6)
    # show_filter drops show col
    out2 = fused_seqpool_cvm_with_conv(jnp.asarray(x), jnp.asarray(segs), 5,
                                       show_filter=True)
    np.testing.assert_allclose(np.asarray(out2), expect[:, 1:], rtol=1e-5,
                               atol=1e-6)
    out3 = fused_seqpool_cvm_with_conv(jnp.asarray(x), jnp.asarray(segs), 5,
                                       use_cvm=False)
    np.testing.assert_allclose(np.asarray(out3), pooled[:, 3:], rtol=1e-5,
                               atol=1e-6)


def test_fused_seqpool_cvm_with_credit():
    rng = np.random.default_rng(10)
    d = 2
    x, segs, _ = _csr(rng, 4, 4 + d)
    pooled = np.zeros((4, 4 + d))
    np.add.at(pooled, segs, x)
    out = fused_seqpool_cvm_with_credit(jnp.asarray(x), jnp.asarray(segs), 4)
    expect = np.concatenate([np.log(pooled[:, :4] + 1), pooled[:, 4:]], axis=1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-6)
    out2 = fused_seqpool_cvm_with_credit(jnp.asarray(x), jnp.asarray(segs), 4,
                                         show_filter=True)
    np.testing.assert_allclose(np.asarray(out2), expect[:, 1:], rtol=1e-5,
                               atol=1e-6)


def test_fused_seqpool_cvm_with_pcoc():
    rng = np.random.default_rng(11)
    d, p = 2, 3
    cvm_offset = 4 + p
    x, segs, _ = _csr(rng, 4, cvm_offset + d)
    pooled = np.zeros((4, cvm_offset + d))
    np.add.at(pooled, segs, x)
    out = fused_seqpool_cvm_with_pcoc(jnp.asarray(x), jnp.asarray(segs), 4,
                                      cvm_offset=cvm_offset, pclk_num=p)
    l = lambda v: np.log(v + 1)
    expect = np.concatenate([
        l(pooled[:, :1]),
        l(pooled[:, 1:2]) - l(pooled[:, :1]),
        l(pooled[:, 4:4 + p]) - l(pooled[:, 2:3]),
        l(pooled[:, 4:4 + p]) - l(pooled[:, 3:4]),
        pooled[:, cvm_offset:]], axis=1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-6)
    assert out.shape == (4, 2 + 2 * p + d)


def test_fused_seqpool_cvm_tradew():
    rng = np.random.default_rng(12)
    d, tn, tid = 3, 2, 1
    x, segs, _ = _csr(rng, 5, 2 + tn + d)
    out = fused_seqpool_cvm_tradew(jnp.asarray(x), jnp.asarray(segs), 5,
                                   trade_num=tn, trade_id=tid)
    pooled = np.zeros((5, 2 + d))
    for i in range(x.shape[0]):
        r = segs[i]
        pooled[r, :2] += x[i, :2]
        pooled[r, 2:] += x[i, 2 + tn:] * x[i, 2 + tid]
    expect = np.concatenate([
        np.log(pooled[:, :1] + 1),
        np.log(pooled[:, 1:2] + 1) - np.log(pooled[:, :1] + 1),
        pooled[:, 2:]], axis=1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-6)


def test_fused_seqpool_cvm_diff_thres_clk_filter():
    rng = np.random.default_rng(13)
    d = 2
    x, segs, _ = _csr(rng, 4, 2 + d)
    out = fused_seqpool_cvm_with_diff_thres(
        jnp.asarray(x), jnp.asarray(segs), 4, slot_threshold=0.5,
        clk_filter=True)
    ref = np.zeros((4, 2 + d))
    for i in range(x.shape[0]):
        show, click = x[i, 0], x[i, 1]
        if (show - click) * 0.2 + click * 1.0 < 0.5:
            continue
        ref[segs[i]] += x[i]
    expect = np.concatenate([
        np.log(ref[:, 1:2] + 1) - np.log(ref[:, :1] + 1), ref[:, 2:]], axis=1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-6)
    assert out.shape == (4, 1 + d)


def test_fused_concat_and_fusion_concat():
    a = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    b = jnp.asarray(np.arange(6, dtype=np.float32).reshape(3, 2))
    out = fused_concat([a, b])
    assert out.shape == (3, 6)
    out2 = fused_concat([a, a], offset=1, length=2)
    np.testing.assert_allclose(np.asarray(out2),
                               np.concatenate([a[:, 1:3], a[:, 1:3]], 1))
    rng = np.random.default_rng(14)
    x1, s1, _ = _csr(rng, 4, 2 + 3)
    x2, s2, _ = _csr(rng, 4, 2 + 2)
    fused = fusion_seqpool_cvm_concat(
        [jnp.asarray(x1), jnp.asarray(x2)],
        [jnp.asarray(s1), jnp.asarray(s2)], 4)
    a1 = fused_seqpool_cvm_full(jnp.asarray(x1), jnp.asarray(s1), 4)
    a2 = fused_seqpool_cvm_full(jnp.asarray(x2), jnp.asarray(s2), 4)
    np.testing.assert_allclose(np.asarray(fused),
                               np.concatenate([a1, a2], axis=1), rtol=1e-6)


def test_quantize_truncation_matches_c_cast():
    v = jnp.asarray([0.1, -0.1, 0.004, -0.004], jnp.float32)
    out = np.asarray(quantize(v, 128))
    expect = np.array([int(x * 128 + 0.5) / 128 for x in
                       [0.1, -0.1, 0.004, -0.004]], np.float32)
    np.testing.assert_allclose(out, expect)


def test_rank_attention2_matches_rank_attention():
    rng = np.random.default_rng(15)
    b, f, c, k = 6, 5, 4, 3
    x = rng.normal(size=(b, f)).astype(np.float32)
    param = rng.normal(size=(k * k, f, c)).astype(np.float32)
    ro = np.zeros((b, 1 + 2 * k), np.int32)
    for i in range(b):
        ro[i, 0] = rng.integers(1, k + 1)
        for j in range(k):
            if rng.random() < 0.7:
                ro[i, 1 + 2 * j] = rng.integers(1, k + 1)
                ro[i, 2 + 2 * j] = rng.integers(0, b)
    out1, _ = rank_attention(jnp.asarray(x), jnp.asarray(ro),
                             jnp.asarray(param), max_rank=k)
    out2 = rank_attention2(jnp.asarray(x), jnp.asarray(ro),
                           jnp.asarray(param.reshape(k * k * f, c)),
                           max_rank=k)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-6)
