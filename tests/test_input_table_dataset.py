"""InputTableDataset tests: string interning at load, stable indices,
lookup_input gather semantics, multi-threaded load consistency."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.data.input_table import (InputTableDataset, lookup_input)
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding.cache import InputTable, ReplicaCache


def _config():
    return DataFeedConfig(
        slots=(SlotConf("url"), SlotConf("feat", avg_len=2.0)),
        batch_size=4)


def _write(tmp_path, name, lines):
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_interning_and_roundtrip(tmp_path):
    cfg = _config()
    f = _write(tmp_path, "a.txt", [
        "1 url:http://a.com feat:11",
        "0 url:http://b.com feat:12 feat:13",
        "1 url:http://a.com feat:14",      # repeated url -> same index
    ])
    ds = InputTableDataset(cfg, ["url"])
    ds.set_filelist([f])
    ds.load_into_memory()
    assert ds.input_table.size == 2
    chunk = ds._merge()
    urls = chunk.sparse_ids["url"]
    # rows 0 and 2 share an interned id; ids are index+1 (nonzero)
    assert urls[0] == urls[2] != urls[1]
    assert urls.min() >= 1
    # feat slot passed through untouched
    np.testing.assert_array_equal(np.sort(chunk.sparse_ids["feat"]),
                                  [11, 12, 13, 14])
    # the table resolves back to the original strings
    idx = int(urls[0]) - 1
    assert ds.input_table.key_at(idx) == "http://a.com"


def test_string_slot_must_be_sparse():
    with pytest.raises(ValueError):
        InputTableDataset(_config(), ["nope"])


def test_empty_string_value_stays_malformed(tmp_path):
    """'url:' must be dropped like the plain svm path drops it — not
    interned as a phantom empty-string feature."""
    cfg = _config()
    f = _write(tmp_path, "m.txt", [
        "1 url: feat:11",              # malformed: dropped
        "0 url:ok feat:12",
    ])
    ds = InputTableDataset(cfg, ["url"])
    ds.set_filelist([f])
    ds.load_into_memory()
    assert ds.num_instances == 1
    assert ds.input_table.size == 1
    assert ds.input_table.key_at(0) == "ok"


def test_no_global_registry_leak(tmp_path):
    from paddlebox_tpu.data import parser as parser_mod
    before = set(parser_mod._REGISTRY)
    for _ in range(5):
        InputTableDataset(_config(), ["url"])
    assert set(parser_mod._REGISTRY) == before


def test_shared_table_across_datasets(tmp_path):
    """Day-over-day loads share one table so indices stay stable (the
    reference keeps the InputTable in the BoxWrapper singleton)."""
    cfg = _config()
    table = InputTable()
    f1 = _write(tmp_path, "d1.txt", ["1 url:x feat:1", "0 url:y feat:2"])
    f2 = _write(tmp_path, "d2.txt", ["1 url:y feat:3", "0 url:z feat:4"])
    d1 = InputTableDataset(cfg, ["url"], table=table)
    d1.set_filelist([f1])
    d1.load_into_memory()
    d2 = InputTableDataset(cfg, ["url"], table=table)
    d2.set_filelist([f2])
    d2.load_into_memory()
    assert table.size == 3
    # 'y' got the same index in both days
    y1 = d1._merge().sparse_ids["url"][1]
    y2 = d2._merge().sparse_ids["url"][0]
    assert y1 == y2


def test_lookup_input_gather(devices8):
    values = np.arange(12, dtype=np.float32).reshape(4, 3)
    cache = ReplicaCache(values)
    # feasigns: 1 -> row 0, 3 -> row 2, 0 -> padding (zeros)
    out = lookup_input(cache, jnp.asarray([1, 3, 0], jnp.int32))
    np.testing.assert_allclose(np.asarray(out),
                               [values[0], values[2], [0, 0, 0]])


def test_multithreaded_load_consistent(tmp_path):
    """Many files loaded by concurrent readers: every occurrence of a
    string maps to one index (lock-sharded insert, box_wrapper.h:151)."""
    cfg = _config()
    files = []
    for i in range(6):
        lines = [f"1 url:site-{j % 7} feat:{j + 1}" for j in range(40)]
        files.append(_write(tmp_path, f"p{i}.txt", lines))
    ds = InputTableDataset(cfg, ["url"])
    ds.set_filelist(files)
    ds.load_into_memory()
    assert ds.input_table.size == 7
    chunk = ds._merge()
    # group rows by url feasign: all rows of one feasign share one string
    ids = chunk.sparse_ids["url"]
    assert set(np.unique(ids)) == set(range(1, 8))
