"""Worker payload for the REAL-PROCESS elastic drill (spawned per
generation by ``python -m paddlebox_tpu.launch --elastic-dir ...``).

Role of the training process under the reference's elastic stack
(``fleet/elastic/manager.py:131-614`` + the launch watcher): join the
cluster at whatever world size the current rank table dictates, RECOVER
from the donefile chain (base + deltas published by earlier
generations), train the remaining passes of the day, and publish
checkpoints as it goes — so a SIGKILL'd peer costs at most the
in-flight pass.

Usage: elastic_drill_worker.py <data_dir> <out_dir> <result_json>
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

DAY = "20260728"
SLOTS = ("user", "item")


def main() -> None:
    data_dir, out_dir, result_json = sys.argv[1:4]
    from paddlebox_tpu.distributed import bootstrap
    bootstrap.initialize()   # PBX_* env from the launcher

    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig
    from paddlebox_tpu.train.day_runner import DayRunner

    ndev = len(jax.devices())        # global across the generation
    mesh = build_mesh(HybridTopology(dp=ndev))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    table_cfg = TableConfig(name="emb", dim=8, learning_rate=0.1)
    # PBX_MULTIHOST_WORLD=N: back the trainer with the multi-host shard
    # tier (N loopback ShardServers + MultiHostStore) instead of the
    # flat FeatureStore. Every elastic generation rebuilds the loopback
    # cluster and recovers it from the SAME donefile chain — the
    # world-agnostic hostshard reload is exactly what a real restarted
    # host does after a membership change (MULTIHOST.md).
    store = None
    mh_world = int(os.environ.get("PBX_MULTIHOST_WORLD", "0"))
    if mh_world:
        from paddlebox_tpu.multihost import (MultiHostStore,
                                             start_local_shards)
        _servers, eps = start_local_shards(mh_world, table_cfg)
        store = MultiHostStore(table_cfg, eps)
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        table_cfg, mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10),
        store=store)
    trainer.init(seed=0)
    runner = DayRunner(trainer, feed, out_dir, data_root=data_dir,
                       split_interval=60, split_per_pass=1,
                       hours=list(range(6)), num_reader_threads=1,
                       shuffle=False,
                       is_rank0=jax.process_index() == 0)
    # Elastic restart contract: every generation recovers the donefile
    # chain first; finished passes are skipped inside train_day.
    runner.recover()
    stats = runner.train_day(DAY)

    if jax.process_index() == 0:
        with open(result_json + ".tmp", "w") as f:
            json.dump({
                "losses": [s["loss"] for s in stats],
                "trained_passes": len(stats),
                "world": jax.process_count(),
                "generation": int(os.environ.get(
                    "PBX_ELASTIC_GENERATION", "-1")),
            }, f)
        os.replace(result_json + ".tmp", result_json)


if __name__ == "__main__":
    main()
