"""Host pass-build benchmark: native KeyIndex + dedup at production scale.

Measures the CPU-side half of the pass lifecycle that SURVEY.md §7 ranks
hard part #1 — "per-pass index build throughput on host" (role of the
reference's 16-way-sharded PreBuildTask, ps_gpu_wrapper.cc:114):

- ``index_build``: fresh upsert of N unique keys into the incremental
  key->row index (native/store.cc pbx_index_upsert; hugepage-backed
  open addressing + software prefetch pipeline).
- ``index_mixed``: a pass-shaped batch (half hits, half new keys).
- ``index_lookup``: the per-batch read path (threaded find).
- ``dedup``: unsorted duplicate-heavy pass keys -> sorted unique
  (native/keymap.cc pbx_dedup_u64; feed_pass role).

Runs entirely on the host (no TPU needed). Prints one JSON line per
metric; ``--json`` prints a single combined object instead.

    python tools/bench_native_store.py [--keys 50000000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=50_000_000)
    ap.add_argument("--batch", type=int, default=8_000_000)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    from paddlebox_tpu.native.build import native_available
    from paddlebox_tpu.native.keymap_py import dedup_keys
    from paddlebox_tpu.native.store_py import KeyIndex, bench_index_build

    if not native_available():
        print(json.dumps({"error": "native library unavailable"}))
        return

    n, b = args.keys, args.batch
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 1 << 62, n, dtype=np.uint64)

    out = {"keys": n}
    # The headline metrics come from the ONE shared definition
    # (store_py.bench_index_build — same as bench.py's
    # host_index_build_keys_per_s / host_index_bulk_build_keys_per_s).
    out["index_build_keys_per_s"] = round(bench_index_build(n))
    # Round 13: sorted-run build (per-chunk dedup → run merge →
    # bulk_build) and the pre-r13 per-key dict walk it is measured
    # against (the ≥10× acceptance baseline).
    out["index_bulk_build_keys_per_s"] = round(
        bench_index_build(n, mode="bulk"))
    out["index_dict_build_keys_per_s"] = round(
        bench_index_build(min(n, 8_000_000), mode="dict"))

    # The remaining metrics reuse a populated index at the same scale.
    idx = KeyIndex()
    idx.reserve(n)
    for lo in range(0, n, 10_000_000):
        idx.upsert(keys[lo:lo + 10_000_000])

    mix = np.concatenate([
        rng.choice(keys, b // 2),
        rng.integers(1 << 62, 1 << 63, b // 2, dtype=np.uint64)])
    rng.shuffle(mix)
    t0 = time.perf_counter()
    rows, n_new = idx.upsert(mix)
    out["index_mixed_keys_per_s"] = round(b / (time.perf_counter() - t0))

    t0 = time.perf_counter()
    r2 = idx.lookup(mix)
    out["index_lookup_keys_per_s"] = round(b / (time.perf_counter() - t0))
    assert np.array_equal(rows, r2), "upsert/lookup row mismatch"

    # Pass-key dedup: 4x duplication factor, like a pass's batch stream.
    dup = rng.choice(keys[:b], b * 4)
    t0 = time.perf_counter()
    uniq = dedup_keys(dup)
    out["dedup_keys_per_s"] = round(dup.size / (time.perf_counter() - t0))
    assert uniq.size <= b and np.all(np.diff(uniq.astype(np.int64)) > 0)

    if args.json:
        print(json.dumps(out))
    else:
        for k, v in out.items():
            print(json.dumps({"metric": k, "value": v}))


if __name__ == "__main__":
    main()
