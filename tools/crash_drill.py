"""Crash drill harness: kill a real training process at a chosen
faultpoint, restart it with ``resume=True``, and diff the final state
against an uninterrupted run.

The donefile protocol's whole value proposition — "a SIGKILL costs at
most the in-flight pass" — is only proven by actually dying. This tool
runs a short 2-pass deepfm day in a subprocess with
``FLAGS_fault_spec='<site>:hit=N:kill'`` so the process SIGKILLs itself
the instant it reaches the chosen site (deterministic — no sleep/poll
races), restarts the same job with recovery enabled, and byte-compares
the final model (dense params digest, sparse store digest, per-pass
losses) against a never-killed reference run.

Usage::

    python tools/crash_drill.py                     # fast 2-site drill
    python tools/crash_drill.py --full              # full site matrix
    python tools/crash_drill.py --matrix multihost  # replicated tier
    python tools/crash_drill.py --site checkpoint/publish --hit 2
    python tools/crash_drill.py --worker DATA OUT RESULT [--resume]

Fast mode's two sites are the tier-1 CI drill
(``tests/test_self_heal.py``); the full matrix is in the slow tier.
``--matrix multihost`` drills the REPLICATED shard tier (MULTIHOST.md):
the worker trains against a replicas=2 loopback cluster, then walks a
host-loss → promote → re-replicate repair — kills land at the
replica-forward window (shard-kill), between store-apply and journal
append (journal-truncate), and inside the promotion role flip
(repair-interrupt); the resumed run must converge byte-identical.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DAY = "20260728"
SLOTS = ("user", "item")
HOURS = (0, 1)
ROWS_PER_SPLIT = 64

# (site, hit) pairs. Fast = the two crash windows that matter most:
# model files written but index not yet swapped (checkpoint/publish)
# and death before any files exist (day_runner/save). The full matrix
# adds every other save/publish-adjacent window.
FAST_SITES = [("day_runner/save", 1), ("checkpoint/publish", 2)]
FULL_SITES = FAST_SITES + [
    ("checkpoint/publish", 1),
    ("day_runner/publish", 1),
    ("day_runner/day_end_save", 1),
    ("day_runner/load", 2),
]
# The replicated multihost tier's crash windows (--matrix multihost):
# shard-kill (die mid replica forward), journal-truncate (die between
# the store apply and the journal append — store ahead of journal),
# repair-interrupt (die inside the promotion role flip), and mid-frame
# (die while a scatter/gather array frame is half-received — the
# receiver's preallocated buffer holds a torn payload that must never
# reach a store).
MULTIHOST_SITES = [
    ("multihost/replica_forward", 1),
    ("multihost/journal_append", 2),
    ("multihost/replica_promote", 1),
    ("rpc/sg_recv", 1),
]
# The incident flight recorder's crash window (--matrix incident):
# die between the bundle's tmp write and its os.replace — the torn
# ``.incident-*.tmp`` must never be listed as a complete bundle, and a
# retried capture must yield exactly one bundle incident_report renders.
INCIDENT_SITE = ("incident/capture", 1)


def write_day(data_root: str, day: str = DAY, hours=HOURS,
              rows_per_split: int = ROWS_PER_SPLIT) -> None:
    """Deterministic tiny day of CTR text data (the test_day_runner
    generator, shared so drill and tests agree on inputs)."""
    import numpy as np
    rng = np.random.default_rng(int(day))
    for h in hours:
        d = os.path.join(data_root, day, f"{h:02d}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "part-00000"), "w") as f:
            for _ in range(rows_per_split):
                feats = {s: rng.integers(1, 120, rng.integers(1, 3))
                         for s in SLOTS}
                click = float(np.mean([(int(v) % 5 == 0)
                                       for vs in feats.values()
                                       for v in vs]))
                label = int(rng.random() < 0.1 + 0.8 * click)
                toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                                for v in vs)
                f.write(f"{label} {toks}\n")


# ---------------------------------------------------------------------------
# worker (runs in the subprocess that gets killed / resumed)
# ---------------------------------------------------------------------------

def _digest(arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        h.update(a.tobytes())
    return h.hexdigest()


def worker_main(data: str, out: str, result: str, *,
                resume: bool, multihost: bool = False) -> None:
    import numpy as np

    from paddlebox_tpu.data import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig
    from paddlebox_tpu.train.day_runner import DayRunner

    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    table = TableConfig(name="emb", dim=8, learning_rate=0.1)
    servers, mh_store = [], None
    if multihost:
        # Replicated loopback cluster: a kill takes the WHOLE process
        # (client, servers, journals) like a dead host+trainer pair;
        # resume stands a fresh cluster up and recovers from the chain.
        from paddlebox_tpu.multihost import (MultiHostStore,
                                             start_local_shards)
        servers, eps = start_local_shards(2, table, replicas=2)
        mh_store = MultiHostStore(table, eps, replicas=2)
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        table, mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10),
        store=mh_store)
    trainer.init(seed=0)
    runner = DayRunner(trainer, feed, out, data_root=data,
                       split_interval=60, split_per_pass=1,
                       hours=list(HOURS), num_reader_threads=2,
                       pipeline_passes=not multihost)
    stats = runner.run_days([DAY], resume=resume)
    if multihost:
        # Host-loss repair walk AFTER the day: kill one host, PROMOTE
        # the survivor (the replica_promote faultpoint fires inside the
        # role flip), then re-replicate to a fresh host — the drill
        # kills at each window and the resumed run must still converge.
        from paddlebox_tpu.multihost.shard_service import ShardServer
        servers[1].kill()
        new_map = mh_store.replica_map.drop_endpoint(
            mh_store.replica_map.all_endpoints()[1])
        servers[0].adopt_replica_map(new_map)
        mh_store.set_replica_map(new_map)
        fresh = ShardServer("127.0.0.1:0", 0, mh_store.ranges, table)
        servers.append(fresh)
        for slot in range(new_map.world):
            new_map = new_map.add_backup(slot, fresh.endpoint)
        for s in (servers[0], fresh):
            s.adopt_replica_map(new_map)
        mh_store.set_replica_map(new_map)
        mh_store.sync_replicas()
        assert mh_store.replica_map.replication == 2

    import jax
    store = trainer.engine.store
    keys = np.sort(store.key_stats()[0])
    vals = store.pull_for_pass(keys)
    payload = {
        "losses": [round(float(s["loss"]), 10)
                   for s in stats.get(DAY, [])],
        "trained_passes": len(stats.get(DAY, [])),
        "num_features": int(store.num_features),
        "dense_digest": _digest(
            [np.ascontiguousarray(x)
             for x in jax.tree.leaves(jax.device_get(trainer.params))]
            + [np.ascontiguousarray(x)
               for x in jax.tree.leaves(
                   jax.device_get(trainer.opt_state))]),
        "store_digest": _digest(
            [keys] + [np.ascontiguousarray(vals[f])
                      for f in sorted(vals)]),
        "records": [[r.day, r.pass_id] for r in runner.ckpt.records()],
    }
    tmp = result + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, result)


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------

def run_worker(data: str, out: str, result: str, *, resume: bool,
               fault_spec: str = "", timeout: float = 300.0,
               log_path: str = "", multihost: bool = False) -> int:
    """Spawn one worker process; returns its exit code (negative =
    killed by that signal, the expected outcome of a kill drill)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["FLAGS_fault_spec"] = fault_spec
    args = [sys.executable, os.path.abspath(__file__), "--worker",
            data, out, result]
    if resume:
        args.append("--resume")
    if multihost:
        args.append("--multihost")
    logf = open(log_path, "ab") if log_path else subprocess.DEVNULL
    try:
        proc = subprocess.run(args, env=env, cwd=REPO, timeout=timeout,
                              stdout=logf, stderr=subprocess.STDOUT)
    finally:
        if log_path:
            logf.close()
    return proc.returncode


def run_reference(workdir: str, *, timeout: float = 300.0,
                  multihost: bool = False) -> dict:
    """Uninterrupted run on a fresh output dir — the parity baseline."""
    data = os.path.join(workdir, "data")
    if not os.path.isdir(os.path.join(data, DAY)):
        write_day(data)
    out = os.path.join(workdir, "ref_out")
    result = os.path.join(workdir, "ref.json")
    rc = run_worker(data, out, result, resume=True, timeout=timeout,
                    log_path=os.path.join(workdir, "ref.log"),
                    multihost=multihost)
    if rc != 0:
        raise RuntimeError(f"reference run failed rc={rc} "
                           f"(see {workdir}/ref.log)")
    with open(result) as f:
        return json.load(f)


def run_drill(workdir: str, site: str, *, hit: int = 1,
              reference: dict | None = None,
              timeout: float = 300.0, multihost: bool = False) -> dict:
    """Kill at ``site`` (hit N), restart with resume, diff vs reference.
    Returns {"ok", "killed_rc", "site", "hit", "drilled", "reference",
    "mismatch"}."""
    data = os.path.join(workdir, "data")
    if not os.path.isdir(os.path.join(data, DAY)):
        write_day(data)
    tag = site.replace("/", "_") + f"_h{hit}"
    out = os.path.join(workdir, f"out_{tag}")
    result = os.path.join(workdir, f"result_{tag}.json")
    log = os.path.join(workdir, f"{tag}.log")

    rc = run_worker(data, out, result, resume=True,
                    fault_spec=f"{site}:hit={hit}:kill",
                    timeout=timeout, log_path=log, multihost=multihost)
    if rc == 0:
        # The site was never reached — a drill that doesn't kill proves
        # nothing and usually means the site moved.
        return {"ok": False, "site": site, "hit": hit, "killed_rc": rc,
                "mismatch": ["faultpoint never reached (rc=0)"]}

    rc2 = run_worker(data, out, result, resume=True, fault_spec="",
                     timeout=timeout, log_path=log, multihost=multihost)
    if rc2 != 0:
        return {"ok": False, "site": site, "hit": hit, "killed_rc": rc,
                "mismatch": [f"resume run failed rc={rc2} (see {log})"]}
    with open(result) as f:
        drilled = json.load(f)
    ref = reference if reference is not None else run_reference(
        workdir, timeout=timeout, multihost=multihost)

    mismatch = []
    for k in ("num_features", "dense_digest", "store_digest", "records"):
        if drilled[k] != ref[k]:
            mismatch.append(
                f"{k}: drilled {drilled[k]!r} != reference {ref[k]!r}")
    # The resumed process only retrains from the crash point on, so its
    # loss list is a SUFFIX of the reference's.
    n = len(drilled["losses"])
    if n and drilled["losses"] != ref["losses"][-n:]:
        mismatch.append(f"losses: {drilled['losses']} != "
                        f"tail of {ref['losses']}")
    return {"ok": not mismatch, "site": site, "hit": hit,
            "killed_rc": rc, "drilled": drilled, "reference": ref,
            "mismatch": mismatch}


def incident_worker(directory: str) -> None:
    """``--worker-incident`` body: arm the flight recorder at DIR and
    force one capture (the drill injects the kill via
    FLAGS_fault_spec)."""
    from paddlebox_tpu.core import faults, flags, incident
    faults.init_from_flags()
    flags.set_flags({"incident_dir": directory})
    path = incident.GLOBAL.trigger("drill", context={"drill": True},
                                   force=True)
    print(json.dumps({"bundle": path}), flush=True)


def run_incident_drill(workdir: str, *, timeout: float = 120.0) -> dict:
    """Drill the ``incident/capture`` window: kill lands after the
    bundle bytes are durable under the tmp name but before the atomic
    rename. Proves a torn bundle is never mistaken for a complete one,
    and that the retried capture completes and renders."""
    import glob as _glob

    def list_bundles(d):
        # Mirrors core/incident.py list_bundles (the parent process
        # runs without PYTHONPATH): complete bundles only — the
        # atomic-rename contract says torn captures are ``.tmp``.
        return sorted(_glob.glob(os.path.join(d, "incident-*.json")))

    inc_dir = os.path.join(workdir, "incidents")
    os.makedirs(inc_dir, exist_ok=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["FLAGS_fault_spec"] = "incident/capture:hit=1:kill"
    args = [sys.executable, os.path.abspath(__file__),
            "--worker-incident", inc_dir]
    rc = subprocess.run(args, env=env, cwd=REPO, timeout=timeout,
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.STDOUT).returncode
    mismatch = []
    if rc == 0:
        mismatch.append("faultpoint never reached (rc=0)")
    if not _glob.glob(os.path.join(inc_dir, ".incident-*.tmp")):
        mismatch.append("kill left no torn .tmp (window moved?)")
    if list_bundles(inc_dir):
        mismatch.append("torn capture listed as a complete bundle")
    env["FLAGS_fault_spec"] = ""
    rc2 = subprocess.run(args, env=env, cwd=REPO, timeout=timeout,
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.STDOUT).returncode
    if rc2 != 0:
        mismatch.append(f"clean capture run failed rc={rc2}")
    bundles = list_bundles(inc_dir)
    if len(bundles) != 1:
        mismatch.append(
            f"want exactly 1 complete bundle, got {len(bundles)}")
    if bundles and not mismatch:
        render = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "incident_report.py"),
             bundles[0]],
            env=env, cwd=REPO, timeout=timeout,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
        if render.returncode != 0:
            mismatch.append(
                f"incident_report render failed rc={render.returncode}")
    return {"ok": not mismatch, "site": INCIDENT_SITE[0],
            "hit": INCIDENT_SITE[1], "killed_rc": rc,
            "mismatch": mismatch}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker", nargs=3,
                    metavar=("DATA", "OUT", "RESULT"))
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--site", help="drill one site")
    ap.add_argument("--hit", type=int, default=1)
    ap.add_argument("--full", action="store_true",
                    help="run the full site matrix (slow)")
    ap.add_argument("--matrix", default="",
                    help="named drill tier: 'multihost' = the "
                         "replicated shard tier's crash windows; "
                         "'incident' = the flight recorder's "
                         "torn-bundle window")
    ap.add_argument("--worker-incident", metavar="DIR",
                    help="(worker) force one incident capture into DIR")
    ap.add_argument("--multihost", action="store_true",
                    help="(worker) train against a replicas=2 loopback "
                         "shard cluster + host-loss repair walk")
    ap.add_argument("--workdir", default="")
    args = ap.parse_args(argv)

    if args.worker:
        worker_main(*args.worker, resume=args.resume,
                    multihost=args.multihost)
        return 0
    if args.worker_incident:
        incident_worker(args.worker_incident)
        return 0

    multihost = args.matrix == "multihost" or args.multihost
    if args.matrix and args.matrix not in ("multihost", "incident"):
        ap.error(f"unknown --matrix tier {args.matrix!r}")

    import tempfile
    workdir = args.workdir or tempfile.mkdtemp(prefix="crash_drill_")
    if args.matrix == "incident":
        t0 = time.time()
        r = run_incident_drill(workdir)
        print(json.dumps({k: r[k] for k in
                          ("ok", "site", "hit", "killed_rc",
                           "mismatch")}), flush=True)
        print(json.dumps({"metric": "crash_drill", "ok": r["ok"],
                          "sites": 1,
                          "wall_s": round(time.time() - t0, 1),
                          "workdir": workdir}), flush=True)
        return 0 if r["ok"] else 1
    sites = ([(args.site, args.hit)] if args.site
             else (MULTIHOST_SITES if multihost
                   else (FULL_SITES if args.full else FAST_SITES)))
    t0 = time.time()
    ref = run_reference(workdir, multihost=multihost)
    results = []
    for site, hit in sites:
        r = run_drill(workdir, site, hit=hit, reference=ref,
                      multihost=multihost)
        results.append(r)
        print(json.dumps({k: r[k] for k in
                          ("ok", "site", "hit", "killed_rc", "mismatch")
                          if k in r}), flush=True)
    ok = all(r["ok"] for r in results)
    print(json.dumps({"metric": "crash_drill",
                      "ok": ok,
                      "sites": len(results),
                      "wall_s": round(time.time() - t0, 1),
                      "workdir": workdir}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
