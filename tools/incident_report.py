"""incident_report: render an incident flight-recorder bundle.

``core/incident.py`` writes one atomically-renamed JSON bundle per
incident (FIRING page alert / watchdog stall / replica eject /
STALE_PRIMARY burst). This tool turns a bundle — or the newest one in
``FLAGS_incident_dir`` — into a human timeline: what fired, which
objective was breached and by how much, what the fleet's trend looked
like going in, which RPCs were in flight, and what the last pass was
doing.

    python tools/incident_report.py /var/incidents/incident-...json
    python tools/incident_report.py /var/incidents           # newest
    python tools/incident_report.py /var/incidents --list
    python tools/incident_report.py bundle.json --json       # re-dump

Torn captures (``.incident-*.tmp`` — the process died mid-write) are
never listed or rendered: complete bundles only ever appear via
``os.replace``, so presence of the final name IS the integrity check.

No jax import — runs anywhere the bundle file is readable.
"""

import argparse
import json
import sys
import time


def _fmt_ts(ts) -> str:
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S UTC",
                             time.gmtime(float(ts)))
    except (TypeError, ValueError):
        return str(ts)


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def resolve_bundle(path: str) -> str:
    """A bundle file, or a directory (newest complete bundle wins)."""
    import os
    if os.path.isdir(path):
        from paddlebox_tpu.core.incident import list_bundles
        bundles = list_bundles(path)
        if not bundles:
            raise SystemExit(f"no complete incident bundles in {path}")
        return bundles[-1]
    return path


def render(bundle: dict) -> None:
    kind = bundle.get("kind", "?")
    print(f"INCIDENT  {kind}")
    print(f"  captured {_fmt_ts(bundle.get('ts'))}  "
          f"host={bundle.get('host', '?')}  pid={bundle.get('pid', '?')}"
          f"  seq={bundle.get('seq', '?')}")
    ctx = bundle.get("context") or {}
    if ctx:
        print("  context  " + "  ".join(f"{k}={v}"
                                        for k, v in sorted(ctx.items())))
    print()

    # Breached objectives first: the alert section names WHY the
    # recorder fired (for alert-triggered bundles the triggering rule
    # rides the context too).
    alerts = bundle.get("alerts") or []
    if alerts:
        print("OBJECTIVES")
        for a in alerts:
            vf = a.get("value_fast")
            vs = a.get("value_slow")
            th = a.get("threshold")

            def g(v):
                return f"{v:g}" if isinstance(v, (int, float)) else "-"

            print(f"  {str(a.get('state', '?')).upper():>8} "
                  f"[{a.get('severity', '?')}] {a.get('name')}: "
                  f"{a.get('metric')} {a.get('direction', 'above')} "
                  f"{g(th)} (fast={g(vf)} slow={g(vs)})")
    else:
        print("OBJECTIVES: none active at capture")
    print()

    # Trend going in: last points of the history ring for whatever
    # moved (nonzero counters / latency windows).
    hist = bundle.get("history") or {}
    pts = hist.get("points") or []
    if pts:
        span = pts[-1]["ts"] - pts[0]["ts"] if len(pts) > 1 else 0.0
        print(f"HISTORY  {len(pts)} points over {span:.0f}s "
              f"(ring {hist.get('label', '?')!r})")
        last = pts[-1]
        moved = sorted(last.get("counters") or {},
                       key=lambda k: -abs(last["counters"][k]))[:8]
        for name in moved:
            print(f"  {name:<44} +{last['counters'][name]:g} "
                  f"in last window")
        for name, d in sorted((last.get("quantiles") or {}).items()):
            from paddlebox_tpu.core.quantiles import LogQuantileDigest
            qs = LogQuantileDigest.from_dict(d).quantiles()
            p99 = qs.get("p99")
            if isinstance(p99, (int, float)):
                print(f"  {name:<44} window p99 {p99:.3f}")
        print()

    # Last reports: what the trainer/quality plane last said.
    for key, label in (("pass_report", "LAST PASS"),
                       ("quality_report", "LAST QUALITY")):
        rep = bundle.get(key)
        if isinstance(rep, dict):
            brief = {k: rep[k] for k in ("kind", "steps", "samples_per_s",
                                         "loss", "auc", "copc", "alarms")
                     if k in rep}
            print(f"{label}  " + json.dumps(brief, default=str))
    print()

    # The RPC plane at capture: in-flight remotes then pollers — a
    # stall bundle names the remote it was stuck on.
    fx = bundle.get("forensics") or {}
    inflight = fx.get("inflight_rpcs") or []
    if isinstance(inflight, list) and inflight:
        print("IN-FLIGHT RPCS")
        for e in inflight:
            if isinstance(e, dict):
                print(f"  {e.get('service')}.{e.get('method')} -> "
                      f"{e.get('endpoint')} "
                      f"age={e.get('age_s', 0):.1f}s")
    pollers = fx.get("rpc_pollers") or []
    if isinstance(pollers, list) and pollers:
        print("RPC POLLERS")
        for p in pollers:
            if isinstance(p, dict):
                print(f"  {p.get('service')}@{p.get('endpoint')} "
                      f"queue={p.get('worker_queue_depth')} "
                      f"lag={p.get('loop_lag_ms', 0)}ms")
    tail = fx.get("trace_tail") or []
    if tail:
        print(f"TRACE TAIL  last {min(len(tail), 10)} of {len(tail)} "
              "events")
        for ev in tail[-10:]:
            if isinstance(ev, dict):
                print(f"  {ev.get('name', '?')} "
                      f"({ev.get('ph', ev.get('kind', '?'))})")
    sys.stdout.flush()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="bundle JSON file, or a directory "
                    "(renders the newest complete bundle)")
    ap.add_argument("--list", action="store_true",
                    help="list complete bundles in the directory")
    ap.add_argument("--json", action="store_true",
                    help="re-dump the bundle as JSON (machine path)")
    args = ap.parse_args(argv)

    if args.list:
        from paddlebox_tpu.core.incident import list_bundles
        for b in list_bundles(args.path):
            print(b)
        return 0
    path = resolve_bundle(args.path)
    bundle = _load(path)
    if args.json:
        print(json.dumps(bundle, default=str))
        return 0
    print(f"bundle: {path}")
    render(bundle)
    return 0


if __name__ == "__main__":
    sys.exit(main())
