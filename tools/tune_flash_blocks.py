"""Flash-attention block-size tuner: sweep (block_q, block_k) tiles at a
given shape on the attached accelerator and report the fastest.

Run when a real TPU is attached (the CPU path ignores blocks):

    python tools/tune_flash_blocks.py --shape gpt   # bench_gpt's shape
    python tools/tune_flash_blocks.py --b 4 --s 2048 --h 16 --d 64

Prints one JSON line per candidate and a final "best" line; apply the
winner via ``FLAGS_flash_block_q/k`` env (every call site reads the
flags — ops/pallas_kernels/flash_attention.py).

Role of the tile-size tuning the reference bakes into its fused
attention CUDA kernels per-arch (fused_multi_transformer_op.cu launch
configs); on TPU the tile choice is the Mosaic grid, so it is a runtime
flag instead of a compile-time template.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SHAPES = {
    # bench_gpt full-scale: d_model 1024, 16 heads, seq 1024, batch 4
    "gpt": dict(b=4, s=1024, h=16, d=64),
    "long": dict(b=1, s=8192, h=16, d=64),
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", choices=sorted(SHAPES), default=None)
    ap.add_argument("--b", type=int, default=4)
    ap.add_argument("--s", type=int, default=1024)
    ap.add_argument("--h", type=int, default=16)
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--blocks", default="128,256,512,1024")
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()
    shp = SHAPES[args.shape] if args.shape else dict(
        b=args.b, s=args.s, h=args.h, d=args.d)

    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.ops.pallas_kernels import flash_attention

    if jax.default_backend() != "tpu":
        print(json.dumps({"error": f"backend is {jax.default_backend()!r}"
                          " — block tuning needs the TPU kernel"}))
        return

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(shp["b"], shp["s"], shp["h"],
                                     shp["d"])), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=q.shape), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=q.shape), jnp.bfloat16)

    def bench(bq, bk) -> float:
        f = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=bk))
        o = f(q, k, v)
        float(np.asarray(o).ravel()[0])       # warm + force completion
        t0 = time.perf_counter()
        for _ in range(args.iters):
            o = f(q, k, v)
        float(np.asarray(o).ravel()[0])
        return (time.perf_counter() - t0) / args.iters

    cands = sorted({min(int(x), shp["s"])
                    for x in args.blocks.split(",")})
    best = None
    for bq, bk in itertools.product(cands, cands):
        try:
            dt = bench(bq, bk)
        except Exception as e:  # noqa: BLE001 - report and keep sweeping
            print(json.dumps({"block_q": bq, "block_k": bk,
                              "error": repr(e)[:200]}), flush=True)
            continue
        print(json.dumps({"block_q": bq, "block_k": bk,
                          "ms": round(dt * 1e3, 3)}), flush=True)
        if best is None or dt < best[0]:
            best = (dt, bq, bk)
    if best:
        print(json.dumps({
            "best": {"block_q": best[1], "block_k": best[2],
                     "ms": round(best[0] * 1e3, 3)},
            "apply": (f"FLAGS_flash_block_q={best[1]} "
                      f"FLAGS_flash_block_k={best[2]}"),
            "shape": shp,
        }))


if __name__ == "__main__":
    main()
