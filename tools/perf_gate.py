"""Cross-run perf regression gate.

Machine-checks a bench record (``bench.py`` JSON) or a ``pass_report``
summary against a previously recorded baseline, with a configurable
noise tolerance per metric, and exits nonzero on regression — so a
throughput or stage-share regression fails a command (CI, the tier-1
suite via tests/test_perf_gate.py), not a future human reading
BASELINE.md.

Both files are arbitrary (possibly nested) JSON; numeric leaves are
flattened to dotted paths (``stage_ms.read``,
``bottleneck.device_idle_frac``) and every path present in BOTH files
whose direction is known is gated:

- **higher-better** (regression = drop below ``base * (1 - tol)``):
  throughput (``*_per_s``/``per_sec``/``value``), ``auc``, cache
  ``hit_rate``, ``overlap_frac``, ``e2e_over_device_only``,
  ``*_rps``, ``*fill_frac``, ``mfu``.
- **lower-better** (regression = rise above ``base * (1 + tol)`` AND by
  more than ``--abs-floor`` — sub-floor wobble on a 0.3 ms stage is
  noise, not signal): ``*_ms``, ``*_s`` walls, ``*_bytes``,
  ``*idle_frac``, ``host_critical_share``, ``blocked_*_frac``,
  ``violations``, ``host_syncs``, ``*overflow``.
- everything else (counts, ids, flags) is ignored.

Usage:

    python tools/perf_gate.py report.json --baseline BASE.json
    python tools/perf_gate.py report.json --baseline BASE.json \
        --tolerance 0.2 --tol stage_ms.read=0.5 --abs-floor 2.0
    python tools/perf_gate.py report.json --write-baseline BASE.json
    python tools/perf_gate.py --smoke      # self-check, no files

Exit codes: 0 = no regression, 1 = regression(s), 2 = usage/self-check
failure. No jax import — the gate runs anywhere in milliseconds.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_TOLERANCE = 0.15
DEFAULT_ABS_FLOOR = 1.0  # lower-better metrics: ignore sub-floor rises

# Suffix tables, checked in order (higher-better first: "samples_per_s"
# must match "_per_s" before the lower-better "_s" wall suffix does).
# The round-13 ingest keys (ingest_rows_per_s, store_build_keys_per_s,
# host_index_[bulk_]build_keys_per_s) gate through "_per_s" — an ingest
# or store-build regression fails the gate like any throughput drop;
# provenance fields (ingest_workers count, store_build_native bool) are
# not rates and stay ungated.
HIGHER_SUFFIXES = ("_per_s", "per_sec", "samples_per_s", "auc",
                   "hit_rate", "overlap_frac", "e2e_over_device_only",
                   "_rps", "mfu", "achieved_gflops_per_chip",
                   # serving micro-batcher: fuller packed batches =
                   # better coalescing (bench serve --clients keys).
                   "fill_frac",
                   # streaming online mode (bench.py online): fewer
                   # trained passes per hour = staler served models.
                   "_per_hour",
                   # model-quality plane (r20): a slot's example
                   # coverage dropping = the slot is going dark.
                   "_coverage")
LOWER_SUFFIXES = ("_ms", "_s", "_bytes", "idle_frac",
                  "host_critical_share", "blocked_up_frac",
                  "blocked_down_frac", "violations", "host_syncs",
                  "overflow",
                  # serving fleet: a growing degraded-path share means
                  # the SLO-shed path is serving more of the traffic.
                  "degraded_frac",
                  # distributed tracing (r19): the rps/keys-per-s cost
                  # of running with the span ring + cluster scrape ON —
                  # telemetry that gets expensive gets turned off.
                  "overhead_frac",
                  # model-quality plane (r20): more drift alarms on an
                  # identical workload = the model got less healthy.
                  "_alarms",
                  # fleet health plane: any FIRING SLO alert on a
                  # healthy bench run is a regression (the bench
                  # asserts 0; the gate keeps it 0).
                  "_firing")
# Exact-name entries (dotted-path last segment).
HIGHER_NAMES = ("value",)  # bench headline — every config is throughput
# graftlint summary JSON (python -m tools.graftlint --summary): finding
# counts are lower-better — gating a new summary against a recorded one
# fails the run when the baseline/pragma surface silently grows.
LOWER_NAMES = ("findings_total", "new", "baselined", "allowed",
               "warnings",
               # bench.py online: a growing post-lifecycle store means
               # TTL/decay stopped bounding the table (the freshness
               # quantiles under event_to_servable_ms gate through the
               # "_ms" suffix like every latency).
               "post_shrink_store_rows",
               # model-quality plane (r20): calibration error is the
               # |actual/adjusted - 1| bucket sweep — lower is better.
               # COPC itself is NOT gated (1.0 is the target; neither
               # direction is monotonic-better), and skew/churn are
               # data provenance, never a regression.
               "calibration_error",
               # autopilot soak (bench.py fleet --trace): any RPC the
               # chaos replay fails is a dropped prediction — the drill
               # asserts 0, the gate keeps it 0. predict_p99_ms gates
               # via the _ms suffix; scale_actions / canary_blocked
               # are how-the-run-went provenance, never gated (an
               # autopilot that acts MORE is not a regression).
               "failed_rpcs")


def flatten(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of nested dicts as dotted paths. Bools, strings,
    lists, and nulls are not gateable and are dropped."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            p = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten(v, p))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)
    return out


# Never gated even though a suffix matches (wall-clock identity, not a
# performance property of the run).
SKIP_NAMES = ("uptime_s", "ts")


def direction(path: str) -> int:
    """+1 higher-better, -1 lower-better, 0 not gated. Segments are
    checked leaf-to-root: the unit often lives in the PARENT key
    (``stage_ms.read``, ``dispatch_ms_quantiles.p99``), so a leaf with
    no recognizable unit inherits its container's."""
    segments = path.split(".")
    if segments[-1] in SKIP_NAMES:
        return 0
    for seg in reversed(segments):
        if seg in HIGHER_NAMES:
            return 1
        if seg in LOWER_NAMES:
            return -1
        for s in HIGHER_SUFFIXES:
            # endswith, or unit-in-the-middle ("dispatch_ms_quantiles").
            if seg.endswith(s) or (s + "_") in seg:
                return 1
        for s in LOWER_SUFFIXES:
            if seg.endswith(s) or (s + "_") in seg:
                return -1
    return 0


def _abs_floor_for(path: str, abs_floor: float) -> float:
    """The absolute floor exists to ignore sub-ms wobble on tiny stage
    timers — it only makes sense for ms/s/bytes-unit metrics. Fractions
    and counters get a nominal 0.01 floor (so +1 violation or a 2-point
    share move past tolerance always counts)."""
    for seg in reversed(path.split(".")):
        for s in ("_ms", "_s", "_bytes"):
            if seg.endswith(s) or (s + "_") in seg:
                return abs_floor
    return 0.01


def compare(report: Dict[str, Any], baseline: Dict[str, Any], *,
            tolerance: float = DEFAULT_TOLERANCE,
            per_metric_tol: Optional[Dict[str, float]] = None,
            abs_floor: float = DEFAULT_ABS_FLOOR
            ) -> Tuple[List[dict], List[dict]]:
    """Returns (checks, regressions): every gated comparison, and the
    subset that regressed. Pure — tests and --smoke drive it directly."""
    rep = flatten(report)
    base = flatten(baseline)
    per_metric_tol = per_metric_tol or {}
    checks: List[dict] = []
    regressions: List[dict] = []
    for path in sorted(set(rep) & set(base)):
        d = direction(path)
        if d == 0:
            continue
        bv, rv = base[path], rep[path]
        tol = per_metric_tol.get(path, tolerance)
        if d > 0:
            bad = rv < bv * (1.0 - tol)
            ratio = rv / bv if bv else None
        else:
            bad = (rv > bv * (1.0 + tol)
                   and (rv - bv) > _abs_floor_for(path, abs_floor))
            ratio = rv / bv if bv else None
        check = {"metric": path, "baseline": bv, "value": rv,
                 "direction": "higher" if d > 0 else "lower",
                 "tolerance": tol, "ratio": ratio,
                 "regressed": bool(bad)}
        checks.append(check)
        if bad:
            regressions.append(check)
    return checks, regressions


def _print_table(checks: List[dict], verbose: bool) -> None:
    hdr = (f"{'metric':<44} {'dir':>6} {'baseline':>12} {'value':>12} "
           f"{'ratio':>8} {'tol':>6}  verdict")
    print(hdr)
    print("-" * len(hdr))
    for c in checks:
        if not verbose and not c["regressed"]:
            continue
        ratio = f"{c['ratio']:.3f}" if c["ratio"] is not None else "-"
        verdict = "REGRESSED" if c["regressed"] else "ok"
        print(f"{c['metric']:<44} {c['direction']:>6} "
              f"{c['baseline']:>12.4g} {c['value']:>12.4g} {ratio:>8} "
              f"{c['tolerance']:>6.2f}  {verdict}")


def smoke() -> int:
    """Self-check of the gate logic (the gate gates itself): a clean
    report must pass, and planted throughput / stage-share / quantile
    regressions must each trip it. Milliseconds, no files, no jax —
    safe as a tier-1 not-slow test."""
    base = {"metric": "deepfm_ctr_e2e_samples_per_sec_per_chip",
            "value": 8587.0,
            "e2e_over_device_only": 0.156,
            "stage_ms": {"read": 120.0, "pack": 60.0, "dispatch": 900.0},
            "bottleneck": {"device_idle_frac": 0.10,
                           "host_critical_share": 0.30},
            "dispatch_ms_quantiles": {"p50": 12.0, "p99": 30.0},
            "ingest_rows_per_s": 250000.0,
            "store_build_keys_per_s": 406447.0,
            "host_index_bulk_build_keys_per_s": 5.6e6,
            # bench serve --clients keys (r14 serving tier).
            "clients": {"c32": {"throughput_rps": 4000.0,
                                "predict_p99_ms": 12.0,
                                "batch_fill_frac": 0.8}},
            # bench serve --replicas keys (r16 fleet tier): aggregate
            # rps higher-better, router route_ms quantiles lower-better
            # (unit in the parent segment), degraded share lower-better;
            # clients/requests are workload provenance and must NOT
            # gate.
            "replicas": {"r2": {"throughput_rps": 7800.0,
                                "route_ms_quantiles": {"p50": 2.0,
                                                       "p99": 9.0},
                                "batch_fill_frac": 0.7,
                                "degraded_frac": 0.0,
                                "clients": 8,
                                "requests": 23400}},
            # bench multihost --hosts keys (r15 multi-host tier):
            # *_bytes_per_s / *_keys_per_s gate higher-better through
            # "_per_s" (checked BEFORE the lower-better "_bytes"/"_s"
            # suffixes), reshard_ms lower-better; reshard_moved_rows
            # is workload provenance and must NOT gate.
            # cross_host_bytes_per_pass (r22 quantized wire) gates
            # lower-better through the unit-in-the-middle "_bytes_"
            # rule — the int8 wire exists to shrink this number.
            "wire": {"f32": {"cross_host_exchange_bytes_per_s": 2.4e8,
                             "exchange_keys_per_s": 2.9e6,
                             "pull_ms": 7.0, "push_ms": 6.6,
                             "cross_host_bytes_per_pass": 3.4e6}},
            # bench multihost overlap keys (r22 overlapped boundary
            # exchange): the hidden-fraction gates higher-better
            # ("overlap_frac"), busy/wait walls lower-better ("_ms").
            "overlap": {"exchange_overlap_frac": 0.95,
                        "exchange_busy_ms": 18.0,
                        "exchange_wait_ms": 0.1,
                        "overlap_round_ms": 26.0},
            "reshard_ms": 13.0,
            "reshard_rows_per_s": 7.6e5,
            "reshard_moved_rows": 10036,
            # bench multihost replicated-tier keys (r18): the read
            # failover blip and repair wall gate lower-better ("_ms"),
            # journal catch-up gates higher-better ("_per_s");
            # failover_failed_pulls is a correctness count the bench
            # itself asserts 0 — not a gateable rate.
            "failover_blip_ms": 420.0,
            "failover_pull_p50_ms": 90.0,
            "repair_ms": 120.0,
            "journal_catchup_rows_per_s": 1.7e6,
            "failover_failed_pulls": 0,
            # bench.py online keys (r17 streaming tier): freshness
            # quantiles gate lower-better ("_ms" in the parent segment),
            # passes_per_hour higher-better, the post-lifecycle row
            # count lower-better; stream_passes/events are workload
            # provenance and must NOT gate.
            "event_to_servable_ms": {"p50": 900.0, "p99": 2500.0},
            "passes_per_hour": 620.0,
            "post_shrink_store_rows": 31000,
            "stream_passes": 12,
            "events": 49152,
            # distributed-tracing overhead keys (r19): the off-vs-on
            # delta gates lower-better ("overhead_frac"), the absolute
            # rates higher-better ("_rps"/"_per_s"); scrape count is
            # workload provenance and must NOT gate.
            # fleet-health-plane keys ride the same telemetry block:
            # the history-sampler/alert-engine rps cost gates lower-
            # better ("overhead_frac") and alerts_firing lower-better
            # ("_firing" — 0 on a healthy bench, any rise gates).
            "telemetry": {"telemetry_overhead_frac": 0.02,
                          "trace_off_rps": 1900.0,
                          "trace_on_rps": 1860.0,
                          "history_on_rps": 1850.0,
                          "history_overhead_frac": 0.03,
                          "alerts_firing": 0,
                          "scrapes": 40},
            # model-quality keys (r20, bench.py online "quality" block):
            # calibration_error gates lower-better (exact-name match —
            # the p99 leaf inherits the parent's direction), alarm
            # counts lower-better ("_alarms"), slot coverage higher-
            # better ("_coverage"); copc targets 1.0 (not monotonic)
            # and skew/churn describe the DATA — all three are
            # provenance and must NOT gate.
            "quality": {"copc": 1.0,
                        "calibration_error": {"p99": 0.05},
                        "quality_alarms": 0,
                        "slot_coverage": 0.99,
                        "skew_top_share": 0.35,
                        "key_churn": 0.5},
            # bench rpc keys (r21 event-loop/mux RPC plane): rates gate
            # higher-better ("_per_s" is checked BEFORE the lower-better
            # "_bytes" suffix, so bytes_per_s gates as a rate), window
            # quantiles lower-better ("_ms"); the mux-over-legacy ratio
            # and frame counts are provenance and must NOT gate.
            "modes": {"mux": {"64kb_o4": {"calls_per_s": 30000.0,
                                          "p50_ms": 0.4,
                                          "p99_ms": 1.2,
                                          "bytes_per_s": 3.9e9}}},
            "mux_over_legacy_at_o4": 2.6,
            "sg_frames": 842,
            # autopilot soak keys (bench.py fleet --trace): a chaos
            # replay that fails an RPC dropped a prediction
            # ("failed_rpcs" exact-name, lower-better) and the merged
            # predict tail must stay bounded ("_ms"); degraded share
            # lower-better; the ACTION counts are how-the-controller-
            # responded provenance and must NOT gate (a run that
            # scales or blocks a canary more is doing its job).
            "soak": {"failed_rpcs": 0,
                     "predict_p99_ms": 12.0,
                     "degraded_frac": 0.0,
                     "scale_actions": 1,
                     "canary_blocked": 1},
            # HBM residency keys (r23 ZeRO-sharded dense state +
            # slot-column offload): measured bytes gate lower-better
            # through the "_bytes" suffix — growing resident state on
            # an identical workload is a memory regression even when
            # throughput holds; the placement strings are provenance
            # (flatten drops strings) and must NOT gate.
            "dense/params_hbm_bytes": 1972808,
            "dense/opt_state_hbm_bytes": 3945620,
            "table/hot_hbm_bytes": 7.97e7,
            "table/slot_hbm_bytes": 8.39e6,
            "dense_zero": "off",            # not gated (string)
            "table_slot_placement": "fused",  # not gated (string)
            "steps_per_dispatch": 4,        # not gated (count)
            "ingest_workers": 8,            # not gated (count)
            "store_build_native": True,     # not gated (bool)
            "sparse_gather_kernel": "auto"}  # not gated (string)
    ok = True

    def expect(name, got, want):
        nonlocal ok
        if got != want:
            ok = False
            print(f"smoke FAIL: {name}: got {got}, want {want}")

    # Identical report: zero regressions.
    _, regs = compare(base, base)
    expect("identical report regressions", len(regs), 0)
    # Within-tolerance wobble: still clean.
    wobble = json.loads(json.dumps(base))
    wobble["value"] *= 0.95
    wobble["stage_ms"]["read"] *= 1.05
    _, regs = compare(wobble, base)
    expect("within-tolerance wobble", len(regs), 0)
    # Planted regressions: throughput halved, a stage share blown up,
    # a tail quantile exploded — each must be named.
    bad = json.loads(json.dumps(base))
    bad["value"] *= 0.5
    bad["stage_ms"]["read"] *= 10.0
    bad["dispatch_ms_quantiles"]["p99"] = 400.0
    bad["bottleneck"]["device_idle_frac"] = 0.85
    bad["ingest_rows_per_s"] *= 0.3
    bad["store_build_keys_per_s"] *= 0.3
    bad["clients"]["c32"]["throughput_rps"] *= 0.4
    bad["clients"]["c32"]["batch_fill_frac"] = 0.2
    bad["ingest_workers"] = 1          # provenance: must NOT gate
    bad["store_build_native"] = False  # provenance: must NOT gate
    bad["wire"]["f32"]["cross_host_exchange_bytes_per_s"] *= 0.3
    bad["wire"]["f32"]["cross_host_bytes_per_pass"] *= 3.0  # wire grew
    bad["overlap"]["exchange_overlap_frac"] = 0.2  # boundary un-hidden
    bad["reshard_ms"] = 200.0
    bad["reshard_moved_rows"] = 99999  # provenance: must NOT gate
    bad["failover_blip_ms"] = 5000.0          # failover got slow
    bad["repair_ms"] = 9000.0                 # repair got slow
    bad["journal_catchup_rows_per_s"] *= 0.2  # catch-up got slow
    bad["replicas"]["r2"]["throughput_rps"] *= 0.4
    bad["replicas"]["r2"]["route_ms_quantiles"]["p99"] = 90.0
    bad["replicas"]["r2"]["degraded_frac"] = 0.5
    bad["replicas"]["r2"]["clients"] = 2      # provenance: must NOT gate
    bad["event_to_servable_ms"]["p99"] = 60000.0  # freshness blown
    bad["passes_per_hour"] = 80.0
    bad["post_shrink_store_rows"] = 500000    # lifecycle stopped bounding
    bad["stream_passes"] = 2                  # provenance: must NOT gate
    bad["telemetry"]["telemetry_overhead_frac"] = 0.4  # tracing got costly
    bad["telemetry"]["history_overhead_frac"] = 0.5  # sampler got costly
    bad["telemetry"]["alerts_firing"] = 2     # bench fleet was unhealthy
    bad["telemetry"]["scrapes"] = 3           # provenance: must NOT gate
    bad["quality"]["calibration_error"]["p99"] = 0.5  # calibration blown
    bad["quality"]["quality_alarms"] = 7              # drift alarms fired
    bad["quality"]["slot_coverage"] = 0.2             # a slot went dark
    bad["quality"]["copc"] = 0.6              # provenance: must NOT gate
    bad["quality"]["skew_top_share"] = 0.9    # provenance: must NOT gate
    bad["quality"]["key_churn"] = 0.9         # provenance: must NOT gate
    bad["modes"]["mux"]["64kb_o4"]["calls_per_s"] *= 0.4  # mux got slow
    bad["modes"]["mux"]["64kb_o4"]["p99_ms"] = 60.0       # tail blown
    bad["mux_over_legacy_at_o4"] = 0.5        # provenance: must NOT gate
    bad["sg_frames"] = 3                      # provenance: must NOT gate
    bad["dense/opt_state_hbm_bytes"] *= 3.0   # ZeRO placement lost
    bad["table/slot_hbm_bytes"] *= 4.0        # slot columns back in HBM
    bad["soak"]["failed_rpcs"] = 3            # chaos replay dropped RPCs
    bad["soak"]["predict_p99_ms"] = 300.0     # soak tail blown
    bad["soak"]["scale_actions"] = 9          # provenance: must NOT gate
    bad["soak"]["canary_blocked"] = 0         # provenance: must NOT gate
    bad["dense_zero"] = "shard"               # provenance: must NOT gate
    bad["table_slot_placement"] = "host"      # provenance: must NOT gate
    _, regs = compare(bad, base)
    names = {r["metric"] for r in regs}
    for want in ("value", "stage_ms.read", "dispatch_ms_quantiles.p99",
                 "bottleneck.device_idle_frac", "ingest_rows_per_s",
                 "store_build_keys_per_s", "clients.c32.throughput_rps",
                 "clients.c32.batch_fill_frac",
                 "wire.f32.cross_host_exchange_bytes_per_s",
                 "wire.f32.cross_host_bytes_per_pass",
                 "overlap.exchange_overlap_frac",
                 "reshard_ms", "failover_blip_ms", "repair_ms",
                 "journal_catchup_rows_per_s",
                 "replicas.r2.throughput_rps",
                 "replicas.r2.route_ms_quantiles.p99",
                 "replicas.r2.degraded_frac",
                 "event_to_servable_ms.p99",
                 "passes_per_hour",
                 "post_shrink_store_rows",
                 "telemetry.telemetry_overhead_frac",
                 "telemetry.history_overhead_frac",
                 "telemetry.alerts_firing",
                 "quality.calibration_error.p99",
                 "quality.quality_alarms", "quality.slot_coverage",
                 "modes.mux.64kb_o4.calls_per_s",
                 "modes.mux.64kb_o4.p99_ms",
                 "dense/opt_state_hbm_bytes",
                 "table/slot_hbm_bytes",
                 "soak.failed_rpcs", "soak.predict_p99_ms"):
        expect(f"planted regression {want!r} detected", want in names,
               True)
    for never in ("ingest_workers", "store_build_native",
                  "reshard_moved_rows", "replicas.r2.clients",
                  "stream_passes", "events", "telemetry.scrapes",
                  "quality.copc", "quality.skew_top_share",
                  "quality.key_churn", "mux_over_legacy_at_o4",
                  "sg_frames", "dense_zero", "table_slot_placement",
                  "soak.scale_actions", "soak.canary_blocked"):
        expect(f"provenance {never!r} not gated", never in names, False)
    # An IMPROVEMENT must never trip the gate.
    good = json.loads(json.dumps(base))
    good["value"] *= 2.0
    good["stage_ms"]["read"] *= 0.1
    _, regs = compare(good, base)
    expect("improvement regressions", len(regs), 0)
    # The abs floor keeps micro-ms noise out.
    tiny = json.loads(json.dumps(base))
    tiny["stage_ms"]["pack"] = 60.9  # +1.5% over tol? no: +0.9ms < floor
    _, regs = compare(tiny, base, tolerance=0.0)
    expect("abs-floor suppresses sub-ms noise", len(regs), 0)
    print("perf_gate --smoke: " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("report", nargs="?",
                    help="bench/pass_report JSON to gate")
    ap.add_argument("--baseline", help="baseline JSON to compare against")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help=f"default relative noise tolerance "
                         f"(default {DEFAULT_TOLERANCE})")
    ap.add_argument("--tol", action="append", default=[],
                    metavar="METRIC=FRAC",
                    help="per-metric tolerance override (dotted path), "
                         "repeatable: --tol stage_ms.read=0.5")
    ap.add_argument("--abs-floor", type=float, default=DEFAULT_ABS_FLOOR,
                    help="lower-better metrics must also rise by more "
                         "than this absolute amount to regress "
                         f"(default {DEFAULT_ABS_FLOOR})")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="snapshot the report as a new baseline file "
                         "and exit 0 (no gating)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the built-in self-check and exit")
    ap.add_argument("--verbose", action="store_true",
                    help="print every gated metric, not just regressions")
    args = ap.parse_args(argv)

    if args.smoke:
        return smoke()
    if not args.report:
        ap.error("pass a report JSON (or --smoke)")
    with open(args.report) as f:
        report = json.load(f)
    if args.write_baseline:
        with open(args.write_baseline, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"baseline written: {args.write_baseline}")
        return 0
    if not args.baseline:
        ap.error("pass --baseline (or --write-baseline / --smoke)")
    with open(args.baseline) as f:
        baseline = json.load(f)
    per_tol = {}
    for t in args.tol:
        if "=" not in t:
            ap.error(f"--tol wants METRIC=FRAC, got {t!r}")
        k, v = t.split("=", 1)
        per_tol[k] = float(v)
    checks, regressions = compare(report, baseline,
                                  tolerance=args.tolerance,
                                  per_metric_tol=per_tol,
                                  abs_floor=args.abs_floor)
    _print_table(checks, args.verbose or bool(regressions))
    print(f"\n{len(checks)} metrics gated, "
          f"{len(regressions)} regression(s)")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
