import sys

from tools.graftlint.cli import main

sys.exit(main())
