"""Finding model, baseline suppression, and the run summary.

A finding's **fingerprint** deliberately excludes line numbers — it is
``pass_id:relpath:code:key`` where ``key`` is the stable subject of the
finding (a flag name, a metric name, a ``Class.attr``, the synced
expression text), so an unrelated edit shifting lines never invalidates
a baseline entry, while moving the same defect to another file does.

``baseline.json`` holds ``{fingerprint: reason}`` entries; with
``--fail-on new`` (the default) only findings NOT in the baseline fail
the run, which is what makes the suite adoptable on a tree with known,
reviewed exceptions. ``--write-baseline`` records the current findings
(preserving existing reasons) — growing it is visible in the summary
JSON's ``baselined`` count, which the trend gate tracks.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

SEV_ERROR = "error"
SEV_WARN = "warn"


@dataclasses.dataclass
class Finding:
    pass_id: str          # e.g. "hot_sync"
    code: str             # e.g. "HS001"
    severity: str         # SEV_ERROR | SEV_WARN
    path: str             # absolute; serialized relative to root
    lineno: int
    message: str
    key: str              # stable subject for the fingerprint
    suppressed_by: Optional[str] = None   # pragma reason, if any
    baselined_reason: Optional[str] = None

    def fingerprint(self, root: str) -> str:
        rel = os.path.relpath(self.path, root) if self.path else "-"
        return f"{self.pass_id}:{rel}:{self.code}:{self.key}"

    def to_dict(self, root: str) -> Dict[str, object]:
        d = {
            "pass": self.pass_id,
            "code": self.code,
            "severity": self.severity,
            "file": os.path.relpath(self.path, root) if self.path else "-",
            "line": self.lineno,
            "message": self.message,
            "fingerprint": self.fingerprint(root),
        }
        if self.suppressed_by is not None:
            d["allowed"] = self.suppressed_by
        if self.baselined_reason is not None:
            d["baselined"] = self.baselined_reason
        return d


class Baseline:
    def __init__(self, entries: Optional[Dict[str, str]] = None):
        self.entries: Dict[str, str] = dict(entries or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls()
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        entries = data.get("entries", {})
        if isinstance(entries, list):  # tolerate the list-of-dicts shape
            entries = {e["fingerprint"]: e.get("reason", "")
                       for e in entries}
        return cls(entries)

    def save(self, path: str) -> None:
        data = {
            "_comment": ("graftlint suppression baseline — every entry "
                         "is a REVIEWED finding with a written reason; "
                         "see STATIC_ANALYSIS.md for the workflow"),
            "entries": dict(sorted(self.entries.items())),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")

    def reason_for(self, fingerprint: str) -> Optional[str]:
        return self.entries.get(fingerprint)


@dataclasses.dataclass
class RunResult:
    findings: List[Finding]           # everything the passes produced
    root: str
    files_scanned: int = 0
    pass_ids: List[str] = dataclasses.field(default_factory=list)

    def apply_baseline(self, baseline: Baseline) -> None:
        for f in self.findings:
            if f.suppressed_by is None:
                reason = baseline.reason_for(f.fingerprint(self.root))
                if reason is not None:
                    f.baselined_reason = reason

    @property
    def active(self) -> List[Finding]:
        """Findings not suppressed by a pragma."""
        return [f for f in self.findings if f.suppressed_by is None]

    @property
    def new(self) -> List[Finding]:
        """Active findings not covered by the baseline."""
        return [f for f in self.active if f.baselined_reason is None]

    def failures(self, fail_on: str) -> List[Finding]:
        if fail_on == "none":
            return []
        if fail_on == "any":
            return [f for f in self.active if f.severity == SEV_ERROR]
        # "new": baselined findings pass; new warnings don't fail either
        return [f for f in self.new if f.severity == SEV_ERROR]

    def summary(self) -> Dict[str, object]:
        """The trend-tracking JSON: a future PR silently growing the
        baseline (or the pragma count) moves these numbers, and
        tools/perf_gate.py gates them like any lower-better metric."""
        per_pass: Dict[str, Dict[str, int]] = {}
        for pid in self.pass_ids:
            per_pass[pid] = {"findings_total": 0, "new": 0,
                             "baselined": 0, "allowed": 0}
        for f in self.findings:
            row = per_pass.setdefault(
                f.pass_id, {"findings_total": 0, "new": 0,
                            "baselined": 0, "allowed": 0})
            row["findings_total"] += 1
            if f.suppressed_by is not None:
                row["allowed"] += 1
            elif f.baselined_reason is not None:
                row["baselined"] += 1
            else:
                row["new"] += 1
        tot = {k: sum(r[k] for r in per_pass.values())
               for k in ("findings_total", "new", "baselined", "allowed")}
        return {
            "findings_total": tot["findings_total"],
            "new": tot["new"],
            "baselined": tot["baselined"],
            "allowed": tot["allowed"],
            "warnings": sum(1 for f in self.findings
                            if f.severity == SEV_WARN),
            "files_scanned": self.files_scanned,
            "per_pass": per_pass,
        }
