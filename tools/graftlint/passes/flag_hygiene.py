"""Pass 2 — flag hygiene.

The 45+ ``FLAGS_*`` names are the system's operator surface; nothing
cross-checked them until now. Against the AST of the flags module and
every reference in the tree:

- ``FH001`` — a referenced flag name (``flags.flag("x")``,
  ``get_flags``/``set_flags`` literals, a ``FLAGS_x`` string in code)
  resolves to no ``define_flag``
- ``FH002`` — a defined flag is never referenced anywhere in code
  (orphan: dead operator surface)
- ``FH003`` — a defined flag appears in none of the configured docs as
  ``FLAGS_<name>`` (undocumented operator surface)
- ``FH004`` — a doc mentions ``FLAGS_x`` for a flag that does not exist
  (doc drift — usually a rename that missed the docs)
- ``FH005`` — a default does not round-trip through the flag's own env
  parser / declared type (checked statically from the AST literal, and
  dynamically via ``flags.validate_all()`` when the flags module is
  importable standalone)

``# graftlint: allow-flag(reason)`` on the ``define_flag`` line
suppresses FH002/FH003 for that flag.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from typing import Dict, List, Optional, Tuple

from tools.graftlint import project as P
from tools.graftlint.findings import Finding, SEV_ERROR, SEV_WARN

PASS_ID = "flag_hygiene"

_FLAGS_IN_STR = re.compile(r"FLAGS_([a-z][a-z0-9_]*)")

# APIs whose first positional arg is a flag name.
_REF_APIS = {"flags.flag": 0, "flag": 0}


def _collect_defines(proj: P.Project, flags_path: str
                     ) -> Dict[str, Tuple[int, ast.Call]]:
    """name -> (lineno, call node) for every define_flag in the module."""
    out: Dict[str, Tuple[int, ast.Call]] = {}
    for mod in proj.modules.values():
        if os.path.abspath(mod.path) != os.path.abspath(flags_path):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = P.call_chain(node.func)
            if chain is None or chain[-1] != "define_flag":
                continue
            if node.args and (name := P.literal_str(node.args[0])):
                out[name] = (node.lineno, node)
    return out


def _static_default_check(name: str, call: ast.Call
                          ) -> Optional[str]:
    """Literal default vs the (inferred or declared) type."""
    if len(call.args) < 2:
        return None
    dflt = call.args[1]
    if not isinstance(dflt, ast.Constant):
        return None  # computed defaults (1 << 20) are fine — typed below
    v = dflt.value
    declared = None
    for kw in call.keywords:
        if kw.arg == "type" and isinstance(kw.value, ast.Name):
            declared = kw.value.id
    if declared is None:
        if v is None:
            return f"flag {name!r} default is None (no inferable type)"
        return None
    pytype = type(v).__name__
    ok = {"bool": ("bool",), "int": ("int", "bool"),
          "float": ("float", "int"), "str": ("str",)}
    if pytype not in ok.get(declared, (declared,)):
        return (f"flag {name!r} default {v!r} ({pytype}) does not "
                f"match declared type {declared}")
    return None


def _dynamic_validate(flags_path: str) -> List[str]:
    """Import the flags module standalone (it must not import the
    package / jax) and run ``validate_all()``. Errors come back as
    strings; an unimportable module or a missing validate_all is
    reported too — the contract is that the flags module stays
    standalone-checkable."""
    import sys
    try:
        spec = importlib.util.spec_from_file_location(
            "_graftlint_flags_probe", flags_path)
        mod = importlib.util.module_from_spec(spec)
        # dataclasses resolves cls.__module__ through sys.modules during
        # class creation — register for the exec, then drop.
        sys.modules["_graftlint_flags_probe"] = mod
        try:
            spec.loader.exec_module(mod)
        finally:
            sys.modules.pop("_graftlint_flags_probe", None)
    except Exception as e:
        return [f"flags module not importable standalone: {e!r}"]
    validate = getattr(mod, "validate_all", None)
    if validate is None:
        return ["flags module has no validate_all() — defaults are "
                "unchecked until first env override"]
    try:
        return list(validate())
    except Exception as e:
        return [f"validate_all() raised: {e!r}"]


def run(proj: P.Project, cfg) -> List[Finding]:
    findings: List[Finding] = []
    flags_path = cfg.abspath(cfg.flags_module)
    defines = _collect_defines(proj, flags_path)
    flags_mod = None
    for mod in proj.modules.values():
        if os.path.abspath(mod.path) == os.path.abspath(flags_path):
            flags_mod = mod

    # ---- code-side references -------------------------------------------
    # name -> [(path, lineno)]
    refs: Dict[str, List[Tuple[str, int]]] = {}

    def add_ref(name: str, path: str, lineno: int) -> None:
        refs.setdefault(name, []).append((path, lineno))

    for sr in proj.string_refs(_REF_APIS):
        if not sr.is_pattern:
            add_ref(sr.value, sr.path, sr.lineno)
    for mod in proj.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                chain = P.call_chain(node.func)
                tail = chain[-1] if chain else None
                if tail in ("get_flags", "set_flags") and node.args:
                    a = node.args[0]
                    items: List[ast.AST] = [a]
                    if isinstance(a, (ast.List, ast.Tuple, ast.Set)):
                        items = list(a.elts)
                    elif isinstance(a, ast.Dict):
                        items = [k for k in a.keys if k is not None]
                    for it in items:
                        s = P.literal_str(it)
                        if s is not None:
                            add_ref(s, mod.path, it.lineno)
            elif (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                for m in _FLAGS_IN_STR.finditer(node.value):
                    add_ref(m.group(1), mod.path, node.lineno)
            elif isinstance(node, ast.JoinedStr):
                for v in node.values:
                    if (isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        for m in _FLAGS_IN_STR.finditer(v.value):
                            add_ref(m.group(1), mod.path, node.lineno)

    # FH001: unresolved references. A FLAGS_ string mention inside the
    # flags module itself (help text narrating another system's flags)
    # still counts — drift there misleads operators the same way.
    for name, sites in sorted(refs.items()):
        if name in defines:
            continue
        # tolerate truncated prefix mentions like "FLAGS_flash_block_"
        if any(d.startswith(name) for d in defines):
            continue
        for path, lineno in sites[:3]:
            mod = _mod_for(proj, path)
            reason = (P.pragma_for(mod, lineno, PASS_ID)
                      if mod else None)
            findings.append(Finding(
                PASS_ID, "FH001", SEV_ERROR, path, lineno,
                f"reference to undefined flag {name!r} "
                "(no define_flag in the flags module)",
                name, suppressed_by=reason))

    # ---- doc-side --------------------------------------------------------
    doc_mentions: Dict[str, List[Tuple[str, int]]] = {}
    for rel in cfg.flag_docs:
        path = cfg.abspath(rel)
        text = P.read_doc(path)
        for i, line in enumerate(text.splitlines(), 1):
            for m in _FLAGS_IN_STR.finditer(line):
                doc_mentions.setdefault(m.group(1), []).append((path, i))

    for name, (lineno, _call) in sorted(defines.items()):
        reason = (P.pragma_for(flags_mod, lineno, PASS_ID)
                  if flags_mod else None)
        if name not in refs:
            findings.append(Finding(
                PASS_ID, "FH002", SEV_ERROR, flags_path, lineno,
                f"flag {name!r} is defined but never referenced in code "
                "(orphaned operator surface)",
                name, suppressed_by=reason))
        if name not in doc_mentions:
            findings.append(Finding(
                PASS_ID, "FH003", SEV_ERROR, flags_path, lineno,
                f"flag {name!r} is undocumented: FLAGS_{name} appears in "
                f"none of {', '.join(cfg.flag_docs)}",
                name, suppressed_by=reason))

    for name, sites in sorted(doc_mentions.items()):
        if name in defines:
            continue
        if any(d.startswith(name) for d in defines):
            continue  # FLAGS_flash_block_{q,k}-style family mention
        path, lineno = sites[0]
        findings.append(Finding(
            PASS_ID, "FH004", SEV_ERROR, path, lineno,
            f"doc mentions FLAGS_{name} but no such flag is defined "
            "(doc drift)", name))

    # ---- defaults --------------------------------------------------------
    for name, (lineno, call) in sorted(defines.items()):
        msg = _static_default_check(name, call)
        if msg:
            findings.append(Finding(
                PASS_ID, "FH005", SEV_ERROR, flags_path, lineno,
                msg, name))
    for msg in _dynamic_validate(flags_path):
        findings.append(Finding(
            PASS_ID, "FH005", SEV_ERROR, flags_path, 1, msg,
            f"validate_all:{msg[:40]}"))
    return findings


def _mod_for(proj: P.Project, path: str) -> Optional[P.ModuleInfo]:
    for mod in proj.modules.values():
        if mod.path == path:
            return mod
    return None
