"""Pass 3 — registry drift: faultpoint sites and metric names vs docs.

ROBUSTNESS.md's site table and OBSERVABILITY.md's metric-name listings
are the operator's index into the fault/telemetry registries; nothing
kept them honest. Cross-checks, both directions:

- ``RD001`` — a ``faults.faultpoint("site")`` literal in code is
  missing from the ROBUSTNESS.md site table
- ``RD002`` — the site table lists a site no code declares (stale doc)
- ``RD003`` — a metric name registered in code
  (``monitor.add/set_stat/set_gauge/observe/observe_quantile``) is not
  documented in any metric doc (literal or pattern match)
- ``RD004`` — *near-miss* (warn): an undocumented code metric is within
  edit distance 2 of a documented one — almost always a typo
- ``RD005`` — (warn) a concrete (wildcard-free) doc metric name matches
  nothing in code — stale doc entry
- ``RD006`` — the self-heal contract: the faults module must keep
  ``InjectedFault`` transient (``transient = True`` and membership in
  ``_TRANSIENT_TYPES``) — otherwise every injected drill turns fatal
  and the retry machinery is silently untested

F-string metric names (``f"fault/{site}_injected"``) become ``*``
patterns and match documented ``fault/<site>_injected`` forms; doc
tokens expand ``{a,b}`` alternation, ``<x>`` and ``...`` wildcards.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint import project as P
from tools.graftlint.findings import Finding, SEV_ERROR, SEV_WARN

PASS_ID = "registry_drift"

_METRIC_APIS = {"monitor.add": 0, "monitor.set_stat": 0,
                "monitor.set_gauge": 0, "monitor.observe": 0,
                "monitor.observe_quantile": 0,
                "add": 0, "set_stat": 0, "set_gauge": 0,
                "observe": 0, "observe_quantile": 0,
                # Instance-mirror helpers (ShardServer / FleetRouter
                # bump their per-server registry AND the global through
                # one call) — a metric name reaching only these is
                # still a registered name.
                "_bump": 0, "_set_gauge": 0, "_observe_q": 0}
# Trace span/instant/counter names share the doc namespace (the
# OBSERVABILITY.md "built-in span names" list): collect them so a doc
# span entry isn't misread as a stale metric — and so a new slash-named
# span needs a doc row like any metric.
_TRACE_APIS = {"trace.span": 0, "trace.instant": 0, "trace.counter": 0}
_FAULT_APIS = {"faults.faultpoint": 0, "faultpoint": 0}


def _edit_distance(a: str, b: str, cap: int = 3) -> int:
    if abs(len(a) - len(b)) > cap:
        return cap + 1
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (ca != cb)))
        if min(cur) > cap:
            return cap + 1
        prev = cur
    return prev[-1]


def _is_metric_shaped(tok: str) -> bool:
    """Expanded doc tokens that are plausibly metric/span names:
    slash-separated identifiers — not code refs (``monitor.add/get``),
    paths (``fleet/box_wrapper.h:395``), URLs, or math
    (``O(log(max/min)/a)``)."""
    tok = tok.strip()
    if "/" not in tok or tok.startswith("/") or tok.endswith("/"):
        return False
    if any(c in tok for c in " ():=,\"'") or "//" in tok:
        return False
    return all(
        seg and "." not in seg
        and re.fullmatch(r"[A-Za-z0-9_*-]+", seg)
        for seg in tok.split("/"))


def _doc_metric_patterns(cfg) -> Dict[str, List[str]]:
    """pattern -> [sources]; every metric-shaped backticked token
    (brace alternation / ``<x>`` / ``...`` expanded BEFORE shape
    filtering, so ``pass/{train,eval}_*`` survives)."""
    out: Dict[str, List[str]] = {}
    for rel in cfg.metric_docs:
        text = P.read_doc(cfg.abspath(rel))
        for tok in P.backtick_tokens(text):
            for pat in P.expand_doc_pattern(tok):
                if _is_metric_shaped(pat):
                    out.setdefault(pat, []).append(rel)
    return out


def globs_intersect(a: str, b: str) -> bool:
    """True when two '*'-glob patterns share at least one concrete
    string (``pass/*_steps`` vs ``pass/train_*`` -> ``pass/train_steps``).
    Plain strings degrade to equality/fnmatch."""
    memo: Dict[Tuple[int, int], bool] = {}

    def go(i: int, j: int) -> bool:
        key = (i, j)
        if key in memo:
            return memo[key]
        memo[key] = False  # cycle guard for ('*','*')
        if i == len(a) and j == len(b):
            res = True
        elif i < len(a) and a[i] == "*":
            res = go(i + 1, j) or (j < len(b) and go(i, j + 1))
        elif j < len(b) and b[j] == "*":
            res = go(i, j + 1) or (i < len(a) and go(i + 1, j))
        elif i < len(a) and j < len(b) and a[i] == b[j]:
            res = go(i + 1, j + 1)
        else:
            res = False
        memo[key] = res
        return res

    return go(0, 0)


def _doc_sites(cfg) -> Set[str]:
    text = P.read_doc(cfg.abspath(cfg.robustness_doc))
    section = P.doc_section(text, cfg.faultpoint_section)
    sites: Set[str] = set()
    for tok in P.backtick_tokens(section):
        # a table cell may hold "`a/b` / `a/c`" — backtick_tokens already
        # split those; keep slash-shaped tokens only
        if "/" in tok and " " not in tok.strip():
            sites.add(tok.strip())
    return sites


def _looks_like_path(tok: str) -> bool:
    return tok.endswith((".py", ".md", ".cc", ".h"))


def run(proj: P.Project, cfg) -> List[Finding]:
    findings: List[Finding] = []

    # ---- faultpoint sites ------------------------------------------------
    code_sites: Dict[str, Tuple[str, int]] = {}
    for sr in proj.string_refs(_FAULT_APIS):
        if sr.is_pattern:
            continue
        # skip call sites inside the faults module itself (the registry's
        # own plumbing passes `site` through, not a literal)
        code_sites.setdefault(sr.value, (sr.path, sr.lineno))
    doc_sites = _doc_sites(cfg)
    doc_path = cfg.abspath(cfg.robustness_doc)

    for site, (path, lineno) in sorted(code_sites.items()):
        if site not in doc_sites:
            mod = _mod_for(proj, path)
            reason = (P.pragma_for(mod, lineno, PASS_ID)
                      if mod else None)
            findings.append(Finding(
                PASS_ID, "RD001", SEV_ERROR, path, lineno,
                f"faultpoint site {site!r} is missing from the "
                f"{cfg.robustness_doc} site table", site,
                suppressed_by=reason))
    for site in sorted(doc_sites - set(code_sites)):
        if _looks_like_path(site):
            continue
        findings.append(Finding(
            PASS_ID, "RD002", SEV_ERROR, doc_path, 1,
            f"{cfg.robustness_doc} site table lists {site!r} but no "
            "faultpoint declares it", site))

    # ---- metric names ----------------------------------------------------
    code_metrics: Dict[str, Tuple[str, int, bool]] = {}
    for sr in (proj.string_refs(_METRIC_APIS)
               + proj.string_refs(_TRACE_APIS)):
        if "/" not in sr.value:
            continue  # monitor.add("counter") bare names are internal
        code_metrics.setdefault(sr.value, (sr.path, sr.lineno,
                                           sr.is_pattern))
    doc_pats = _doc_metric_patterns(cfg)
    doc_literals = [p for p in doc_pats if "*" not in p]

    def documented(name: str, is_pattern: bool) -> bool:
        return any(globs_intersect(name, pat) for pat in doc_pats)

    for name, (path, lineno, is_pat) in sorted(code_metrics.items()):
        if documented(name, is_pat):
            continue
        mod = _mod_for(proj, path)
        reason = P.pragma_for(mod, lineno, PASS_ID) if mod else None
        near = None
        if not is_pat:
            best = min(doc_literals, default=None,
                       key=lambda d: _edit_distance(name, d))
            if best is not None and _edit_distance(name, best) <= 2:
                near = best
        if near is not None:
            findings.append(Finding(
                PASS_ID, "RD004", SEV_WARN, path, lineno,
                f"metric {name!r} is undocumented but is within edit "
                f"distance 2 of documented {near!r} — typo?", name,
                suppressed_by=reason))
        else:
            findings.append(Finding(
                PASS_ID, "RD003", SEV_ERROR, path, lineno,
                f"metric {name!r} is documented in none of "
                f"{', '.join(cfg.metric_docs)}", name,
                suppressed_by=reason))

    code_names = list(code_metrics)
    code_literals = [n for n, (_, _, is_pat) in code_metrics.items()
                     if not is_pat]
    for pat in sorted(doc_literals):
        if pat in doc_sites or pat in code_sites:
            continue  # faultpoint sites share the doc namespace
        hit = any(globs_intersect(pat, cn) for cn in code_names)
        if not hit and any(_edit_distance(pat, cn) <= 2
                           for cn in code_literals):
            continue  # the RD004 near-miss already covers this typo
        if not hit:
            findings.append(Finding(
                PASS_ID, "RD005", SEV_WARN,
                cfg.abspath(cfg.metric_docs[0]), 1,
                f"doc metric {pat!r} matches no registered metric in "
                "code (stale doc entry?)", pat))

    # ---- transient contract ---------------------------------------------
    findings.extend(_check_transient_contract(proj, cfg))
    return findings


def _check_transient_contract(proj: P.Project, cfg) -> List[Finding]:
    """InjectedFault must stay transient, or drills stop proving the
    self-heal loop. Located by finding the module that defines
    ``is_transient`` + ``InjectedFault``; absent module -> no check
    (fixture projects)."""
    for mod in proj.modules.values():
        cls = mod.classes.get("InjectedFault")
        has_fn = any(q.endswith(":is_transient") for q in mod.functions)
        if cls is None or not has_fn:
            continue
        out: List[Finding] = []
        transient_attr = False
        for node in cls.node.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "transient"
                            for t in node.targets)
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True):
                transient_attr = True
        in_types = False
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "_TRANSIENT_TYPES"
                            for t in node.targets)):
                for el in ast.walk(node.value):
                    if (isinstance(el, ast.Name)
                            and el.id == "InjectedFault"):
                        in_types = True
        if not (transient_attr or in_types):
            out.append(Finding(
                PASS_ID, "RD006", SEV_ERROR, mod.path, cls.node.lineno,
                "InjectedFault is no longer classified transient "
                "(neither `transient = True` nor membership in "
                "_TRANSIENT_TYPES) — injected drills would stop "
                "exercising the pass-retry loop", "InjectedFault"))
        return out
    return []


def _mod_for(proj: P.Project, path: str) -> Optional[P.ModuleInfo]:
    for mod in proj.modules.values():
        if mod.path == path:
            return mod
    return None
