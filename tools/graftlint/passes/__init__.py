"""Pass registry. Adding a pass = one module with ``PASS_ID`` and
``run(project, config) -> List[Finding]``, plus a row here (and a
fixture in tests/test_graftlint.py — see STATIC_ANALYSIS.md)."""

from tools.graftlint.passes import (flag_hygiene, hot_sync,
                                    lock_discipline, registry_drift,
                                    replay_purity)

ALL_PASSES = {
    hot_sync.PASS_ID: hot_sync.run,
    flag_hygiene.PASS_ID: flag_hygiene.run,
    registry_drift.PASS_ID: registry_drift.run,
    lock_discipline.PASS_ID: lock_discipline.run,
    replay_purity.PASS_ID: replay_purity.run,
}
