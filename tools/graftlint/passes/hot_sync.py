"""Pass 1 — hot-path implicit device→host sync detector.

BENCH_r02's host-bound breakdown (e2e at 15.6% of device-only) is why
the dispatch loop's "zero host syncs per block" discipline exists; this
pass keeps it true without re-running a TPU bench. Within every
function reachable from the declared hot-path roots it infers which
local names hold **device values** (results of ``jnp.*``/``lax.*``
calls, ``jax.device_put``, compiled-step handles like
``self._step_fn(...)``, params annotated ``jax.Array``, and anything
propagated from them through assignment / arithmetic / subscript /
tuple-unpack), then flags the operations that force a transfer or a
tracer-boolization:

- ``HS001`` — ``float()/int()/bool()/len()`` on a device value
- ``HS002`` — ``.item()/.tolist()`` on a device value
- ``HS003`` — ``np.asarray()/np.array()`` on a device value
- ``HS004`` — ``jax.device_get(...)`` / ``.block_until_ready()``
  anywhere in hot code (always an explicit sync)
- ``HS005`` — ``if``/``while``/``assert``/ternary truth-test on a
  device value (host sync at runtime; a TracerBoolConversionError
  inside jit)
- ``HS006`` — ``for`` iteration over a device value (one sync per
  element)

Intentional syncs (the deferred finite-vector fetch, the pass-end stat
reduction) carry ``# graftlint: allow-sync(<reason>)`` pragmas on the
flagged line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from tools.graftlint import project as P
from tools.graftlint.findings import Finding, SEV_ERROR, SEV_WARN

PASS_ID = "hot_sync"

_DEVICE_MODULES = {"jnp", "lax"}
_SYNC_BUILTINS = {"float", "int", "bool", "len"}
_SYNC_METHODS = {"item", "tolist"}
_NP_SYNCS = {"asarray", "array"}
# jnp/lax functions that return HOST values (static shape/type queries)
_HOST_RESULT_FNS = {"axis_size", "result_type", "dtype", "ndim",
                    "shape_dtype_struct", "eval_shape"}


def _ann_is_device(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    try:
        txt = ast.unparse(ann)
    except Exception:
        return False
    return ("jax.Array" in txt or "jnp.ndarray" in txt
            or "jnp.Array" in txt)


class _DeviceInference(ast.NodeVisitor):
    """One function body: track device-valued local names, flag syncs."""

    def __init__(self, fi: P.FunctionInfo, cfg, findings: List[Finding]):
        self.fi = fi
        self.cfg = cfg
        self.findings = findings
        self.device: Set[str] = set()
        node = fi.node
        # nested inside a step builder -> a jit-traced body: every
        # parameter is a tracer
        local = fi.qualname.split(":", 1)[1].split(".")
        traced = any(seg in cfg.traced_parents for seg in local[:-1])
        args = getattr(node, "args", None)
        if args is not None:
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)):
                if traced or _ann_is_device(a.annotation):
                    self.device.add(a.arg)

    # -- device-ness of an expression -------------------------------------

    def is_device(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.device
        if isinstance(node, ast.Call):
            return self.call_is_device(node)
        if isinstance(node, ast.Subscript):
            return self.is_device(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_device(node.left) or self.is_device(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_device(node.operand)
        if isinstance(node, ast.IfExp):
            return self.is_device(node.body) or self.is_device(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_device(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_device(node.value)
        return False

    def call_is_device(self, node: ast.Call) -> bool:
        chain = P.call_chain(node.func)
        if chain is None:
            # call of a call: self._sync_params_fn()(params)
            if isinstance(node.func, ast.Call):
                return self.call_is_device(node.func)
            return False
        head = chain[0]
        if chain[-1] in _HOST_RESULT_FNS:
            return False
        if head in _DEVICE_MODULES:
            return True
        if head == "jax":
            if len(chain) >= 2 and chain[1] in ("device_get",):
                return False  # host result (flagged separately)
            return len(chain) >= 2 and chain[1] in (
                "device_put", "jit", "vmap", "pmap")
        if head == "np" or head == "numpy":
            return False
        last = chain[-1]
        for suf in self.cfg.device_fn_suffixes:
            if last.endswith(suf):
                return True
        if isinstance(node.func, ast.Name) and node.func.id in self.device:
            return True
        return False

    # -- assignment propagation -------------------------------------------

    def _assign_names(self, target: ast.AST, is_dev: bool) -> None:
        if isinstance(target, ast.Name):
            if is_dev:
                self.device.add(target.id)
            else:
                self.device.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_names(e, is_dev)
        elif isinstance(target, ast.Starred):
            self._assign_names(target.value, is_dev)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        is_dev = self.is_device(node.value)
        for t in node.targets:
            self._assign_names(t, is_dev)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if self.is_device(node.value):
            self._assign_names(node.target, True)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is not None:
            self._assign_names(node.target, self.is_device(node.value))

    # -- nested defs are analyzed as their own reachable functions ---------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.fi.node:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    # -- sync sites --------------------------------------------------------

    def _flag(self, node: ast.AST, code: str, msg: str, key: str,
              severity: str = SEV_ERROR) -> None:
        lineno = getattr(node, "lineno", self.fi.lineno)
        reason = P.pragma_for(self.fi.module, lineno, PASS_ID)
        self.findings.append(Finding(
            PASS_ID, code, severity, self.fi.path, lineno,
            f"{msg} (in hot-path function {self.fi.qualname})",
            key, suppressed_by=reason))

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        chain = P.call_chain(node.func)
        if chain is None:
            return
        key = f"{self.fi.qualname}:{_src(node)}"
        if (len(chain) == 1 and chain[0] in _SYNC_BUILTINS
                and len(node.args) >= 1 and self.is_device(node.args[0])):
            self._flag(node, "HS001",
                       f"implicit device→host sync: {chain[0]}() on a "
                       f"device value `{_src(node.args[0])}`", key)
        elif (len(chain) >= 2 and chain[-1] in _SYNC_METHODS
                and self.is_device(node.func.value)):
            self._flag(node, "HS002",
                       f".{chain[-1]}() syncs the device value "
                       f"`{_src(node.func.value)}` to the host", key)
        elif (len(chain) == 2 and chain[0] in ("np", "numpy")
                and chain[1] in _NP_SYNCS
                and len(node.args) >= 1 and self.is_device(node.args[0])):
            self._flag(node, "HS003",
                       f"np.{chain[1]}() on a device value "
                       f"`{_src(node.args[0])}` forces a transfer", key)
        elif chain[-1] == "block_until_ready" or (
                len(chain) >= 2 and chain[0] == "jax"
                and chain[1] == "device_get"):
            self._flag(node, "HS004",
                       f"explicit device sync `{_src(node)}` on a "
                       "hot path", key)

    def _flag_truth(self, test: ast.AST, ctx: str) -> None:
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return  # identity checks (x is not None) never sync
        dev = None
        if self.is_device(test):
            dev = test
        elif isinstance(test, ast.Compare) and (
                self.is_device(test.left)
                or any(self.is_device(c) for c in test.comparators)):
            dev = test
        elif isinstance(test, ast.BoolOp):
            for v in test.values:
                if self.is_device(v):
                    dev = v
                    break
        if dev is not None:
            self._flag(test, "HS005",
                       f"truth-test on a device value `{_src(dev)}` in "
                       f"{ctx} (host sync; TracerBoolConversionError "
                       "inside jit)",
                       f"{self.fi.qualname}:{ctx}:{_src(dev)}")

    def visit_If(self, node: ast.If) -> None:
        self._flag_truth(node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._flag_truth(node.test, "while")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._flag_truth(node.test, "assert")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._flag_truth(node.test, "ternary")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if self.is_device(node.iter):
            self._flag(node.iter, "HS006",
                       f"iterating device value `{_src(node.iter)}` "
                       "syncs per element",
                       f"{self.fi.qualname}:for:{_src(node.iter)}",
                       severity=SEV_WARN)
        self.generic_visit(node)


def _src(node: ast.AST) -> str:
    try:
        s = ast.unparse(node)
    except Exception:
        s = "<expr>"
    return s if len(s) <= 60 else s[:57] + "..."


def run(proj: P.Project, cfg) -> List[Finding]:
    findings: List[Finding] = []
    reachable = proj.reachable(cfg.hot_roots)
    for qual in sorted(reachable):
        fi = proj.functions.get(qual)
        if fi is None:
            continue
        inf = _DeviceInference(fi, cfg, findings)
        inf.visit(fi.node)
    return findings
