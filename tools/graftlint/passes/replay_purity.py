"""Pass 5 — replay purity.

PR 5's guarantee is that a retried pass replays **bit-identical** to an
unfailed run. That holds only while nothing on the replay path consults
a nondeterministic source. Within every function reachable from the
self-heal replay roots (``day_runner.train_pass``'s retry loop, the
pass engine, the device store):

- ``RP001`` — wall-clock state source: ``time.time``/``time_ns``,
  ``datetime.now``/``utcnow``/``today``. (``time.perf_counter``/
  ``monotonic``/``sleep`` are allowed — they feed telemetry and
  backoff, not state; a perf_counter value flowing into model state
  would be a bug this pass cannot see, which STATIC_ANALYSIS.md calls
  out.)
- ``RP002`` — randomness: the global ``random`` module, legacy
  ``np.random.*`` global-state calls, seedless
  ``np.random.default_rng()``, ``os.urandom``, ``uuid.uuid1/4``,
  ``secrets.*``.
- ``RP003`` — (warn) nondeterministic iteration: ``for`` over a value
  built as a ``set`` in the same function, or ``list(set(...))`` /
  ``tuple(set(...))`` (set order is hash-seed-dependent across
  processes — a replay in a restarted worker walks a different order).

Intentional sites (timestamps embedded as *metadata*, never state)
carry ``# graftlint: allow-replay(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.graftlint import project as P
from tools.graftlint.findings import Finding, SEV_ERROR, SEV_WARN

PASS_ID = "replay_purity"

_WALL_CLOCK = {
    ("time", "time"), ("time", "time_ns"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
}
_RANDOM_HEADS = {"random", "secrets"}
_NP_RANDOM = {"rand", "randn", "randint", "shuffle", "permutation",
              "choice", "random", "uniform", "normal", "sample", "seed",
              "random_sample", "bytes"}
_RANDOM_ALLOWED = {"Random", "SystemRandom"}  # explicit-seed instances


class _PurityVisitor(ast.NodeVisitor):
    def __init__(self, fi: P.FunctionInfo, findings: List[Finding]):
        self.fi = fi
        self.findings = findings
        self.set_vars: Set[str] = set()

    def _flag(self, node: ast.AST, code: str, msg: str,
              severity: str = SEV_ERROR) -> None:
        lineno = getattr(node, "lineno", self.fi.lineno)
        reason = P.pragma_for(self.fi.module, lineno, PASS_ID)
        try:
            expr = ast.unparse(node)[:60]
        except Exception:
            expr = "<expr>"
        self.findings.append(Finding(
            PASS_ID, code, severity, self.fi.path, lineno,
            f"{msg} (replay-reachable function {self.fi.qualname})",
            f"{self.fi.qualname}:{expr}", suppressed_by=reason))

    def _is_set_expr(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            chain = P.call_chain(node.func)
            if chain == ("set",):
                return True
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
            return (self._is_set_expr(node.left)
                    or self._is_set_expr(node.right))
        if isinstance(node, ast.Name):
            return node.id in self.set_vars
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        is_set = self._is_set_expr(node.value)
        for t in node.targets:
            if isinstance(t, ast.Name):
                (self.set_vars.add if is_set
                 else self.set_vars.discard)(t.id)

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        chain = P.call_chain(node.func)
        if chain is None:
            return
        tail2 = tuple(chain[-2:]) if len(chain) >= 2 else None
        if tail2 in _WALL_CLOCK:
            self._flag(node, "RP001",
                       f"wall-clock call `{'.'.join(chain)}()` on the "
                       "replay path (nondeterministic across retries)")
            return
        head = chain[0]
        if (head in _RANDOM_HEADS and len(chain) >= 2
                and chain[1] not in _RANDOM_ALLOWED):
            self._flag(node, "RP002",
                       f"global randomness `{'.'.join(chain)}()` on the "
                       "replay path")
            return
        if (len(chain) >= 3 and head in ("np", "numpy")
                and chain[1] == "random" and chain[2] in _NP_RANDOM):
            self._flag(node, "RP002",
                       f"legacy global-RNG `{'.'.join(chain)}()` on the "
                       "replay path (use a seeded Generator)")
            return
        if (len(chain) >= 3 and head in ("np", "numpy")
                and chain[1] == "random" and chain[2] == "default_rng"
                and not node.args):
            self._flag(node, "RP002",
                       "seedless np.random.default_rng() on the replay "
                       "path")
            return
        if tail2 == ("os", "urandom") or (
                head == "uuid" and len(chain) >= 2
                and chain[1] in ("uuid1", "uuid4")):
            self._flag(node, "RP002",
                       f"entropy source `{'.'.join(chain)}()` on the "
                       "replay path")
            return
        if (chain in (("list",), ("tuple",)) and node.args
                and self._is_set_expr(node.args[0])):
            self._flag(node, "RP003",
                       f"`{chain[0]}(set(...))` materializes "
                       "hash-order-dependent sequence", SEV_WARN)

    def visit_For(self, node: ast.For) -> None:
        if self._is_set_expr(node.iter):
            self._flag(node.iter, "RP003",
                       "iteration over a set (hash-order-dependent) on "
                       "the replay path — use sorted()", SEV_WARN)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.fi.node:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def run(proj: P.Project, cfg) -> List[Finding]:
    findings: List[Finding] = []
    reachable = proj.reachable(cfg.replay_roots)
    for qual in sorted(reachable):
        fi = proj.functions.get(qual)
        if fi is not None:
            _PurityVisitor(fi, findings).visit(fi.node)
    return findings
