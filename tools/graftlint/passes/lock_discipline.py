"""Pass 4 — lock discipline across the threaded pipeline modules.

The pipeline is a thicket of producer/builder/watchdog/RPC threads
(``pass_engine``, ``ctr_trainer``, ``transport``, ``ps``, ``watchdog``,
…). Three checks, all per-module with a project call graph for
reachability:

- ``LD001`` — a ``self.<attr>`` written from thread-entry-reachable
  code and accessed from other code where **no common lock** covers
  both sides. Writes in ``__init__`` (pre-``start()``) don't count;
  attributes that *are* synchronization objects (locks, events,
  queues, semaphores) are exempt — they are the mechanism, not the
  state. One finding per (class, attr), listing witness sites.
- ``LD002`` — the lock-acquisition-order graph (``with self.A:`` nested
  inside ``with self.B:``, plus one-level call propagation) has a
  cycle: a deadlock candidate.
- ``LD003`` — (warn) ``Event.wait()``/``Condition.wait()`` with no
  timeout in thread-reachable code: an un-wakeable park that turns a
  missed ``set()`` into a hang the watchdog must break.

The convention already in the tree is honored: a method named
``*_locked`` is asserted to run under its class lock and counts as
locked on both sides. ``# graftlint: allow-lock(reason)`` suppresses a
finding at the attribute's first unlocked write (LD001) or the wait
site (LD003).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.graftlint import project as P
from tools.graftlint.findings import Finding, SEV_ERROR, SEV_WARN

PASS_ID = "lock_discipline"

_SYNC_CTORS = (
    "threading.Lock", "threading.RLock", "threading.Event",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Lock", "RLock", "Event", "Condition",
    "Semaphore", "BoundedSemaphore", "Queue", "SimpleQueue",
)
_LOCK_CTORS = ("threading.Lock", "threading.RLock",
               "threading.Condition", "Lock", "RLock", "Condition")
_EVENT_CTORS = ("threading.Event", "threading.Condition", "Event",
                "Condition")
_THREAD_CTORS = ("threading.Thread", "Thread", "threading.Timer",
                 "Timer")


@dataclasses.dataclass
class _Access:
    func: P.FunctionInfo
    lineno: int
    kind: str            # "read" | "write"
    locks: Tuple[str, ...]  # lock names held (self attrs / globals)


def _thread_entries(proj: P.Project) -> Set[str]:
    """Qualnames of functions used as Thread targets (or run() methods
    of Thread subclasses). The RPC plane's poller loops
    (``FramedRPCServer._poll_loop``, the mux ``_reader_loop``) enter
    here like any other root: everything the ONE poller thread owns —
    selector registrations, ``_Conn`` state, queue-depth counters — is
    thread-reachable and analyzed; single-writer poller-owned slots
    carry ``allow-lock`` pragmas naming the ownership argument."""
    entries: Set[str] = set()
    for mod in proj.modules.values():
        for qual, fi in mod.functions.items():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                chain = P.call_chain(node.func)
                if chain is None or ".".join(chain) not in _THREAD_CTORS:
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    tchain = P.call_chain(kw.value)
                    if tchain is None:
                        continue
                    for target in proj.resolve_call(tchain, fi):
                        entries.add(target.qualname)
        for cname, ci in mod.classes.items():
            if any(b in ("Thread",) for b in ci.bases):
                run_m = ci.methods.get("run")
                if run_m is not None:
                    entries.add(run_m.qualname)
    return entries


class _LockWalker(ast.NodeVisitor):
    """Walk one function recording attribute accesses + held locks +
    lock-order edges + untimed waits."""

    def __init__(self, fi: P.FunctionInfo, lock_attrs: Set[str],
                 event_attrs: Set[str]):
        self.fi = fi
        self.lock_attrs = lock_attrs      # names known to be locks
        self.event_attrs = event_attrs    # names known to be events/conds
        self.held: List[str] = []
        # if the convention says the whole method runs under the class
        # lock, record a synthetic hold
        if fi.name.endswith("_locked"):
            self.held.append("<class-lock>")
        self.accesses: List[Tuple[str, _Access]] = []  # (attr, access)
        self.acquired: List[str] = []        # all locks this fn acquires
        self.order_edges: List[Tuple[str, str, int]] = []
        self.waits: List[Tuple[int, str]] = []
        self.calls_with_locks: List[Tuple[Tuple[str, ...], Tuple[str, ...],
                                          int]] = []

    def _lock_name(self, node: ast.AST) -> Optional[str]:
        chain = P.call_chain(node)
        if chain is None:
            return None
        name = ".".join(chain)
        tail = chain[-1]
        if tail in self.lock_attrs or name in self.lock_attrs:
            return tail
        return None

    def visit_With(self, node: ast.With) -> None:
        names = []
        for item in node.items:
            ln = self._lock_name(item.context_expr)
            if ln is not None:
                names.append(ln)
        for ln in names:
            if self.held and self.held[-1] != ln:
                self.order_edges.append((self.held[-1], ln, node.lineno))
            self.held.append(ln)
            self.acquired.append(ln)
        for stmt in node.body:
            self.visit(stmt)
        for _ in names:
            self.held.pop()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            kind = ("write" if isinstance(node.ctx,
                                          (ast.Store, ast.Del))
                    else "read")
            self.accesses.append((node.attr, _Access(
                self.fi, node.lineno, kind, tuple(self.held))))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        chain = P.call_chain(node.func)
        if chain is not None:
            if (chain[-1] == "wait" and len(chain) >= 2
                    and not node.args
                    and not any(kw.arg == "timeout"
                                for kw in node.keywords)):
                owner = chain[-2]
                if owner in self.event_attrs:
                    self.waits.append((node.lineno, ".".join(chain)))
            self.calls_with_locks.append(
                (chain, tuple(self.held), node.lineno))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.fi.node:
            self.generic_visit(node)
        # nested defs analyzed separately

    visit_AsyncFunctionDef = visit_FunctionDef


def run(proj: P.Project, cfg) -> List[Finding]:
    findings: List[Finding] = []
    entries = _thread_entries(proj)
    if not entries:
        return findings
    thread_reach = set(proj.reachable(
        [f"{q.split(':', 1)[0]}:{q.split(':', 1)[1]}" for q in entries]))

    # global sets of lock-ish / event-ish attr names, per class walk
    all_lock_attrs: Set[str] = set()
    all_event_attrs: Set[str] = set()
    for infos in proj.classes.values():
        for ci in infos:
            for attr, ctor in ci.attr_ctors.items():
                if ctor in _LOCK_CTORS:
                    all_lock_attrs.add(attr)
                if ctor in _EVENT_CTORS:
                    all_event_attrs.add(attr)
    # module-level locks: NAME = threading.Lock()
    for mod in proj.modules.values():
        for node in mod.tree.body:
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                chain = P.call_chain(node.value.func)
                if chain and ".".join(chain) in _LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            all_lock_attrs.add(t.id)

    order_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    fn_acquires: Dict[str, Set[str]] = {}
    fn_calls: Dict[str, List[Tuple[Tuple[str, ...], Tuple[str, ...],
                                   int]]] = {}

    # ---- per-class shared-attribute analysis -----------------------------
    for infos in proj.classes.values():
        for ci in infos:
            methods = {q: fi for q, fi in ci.module.functions.items()
                       if fi.cls == ci.name}
            if not methods:
                continue
            t_meths = {q for q in methods if q in thread_reach}
            # walk every method once
            per_attr: Dict[str, List[_Access]] = {}
            for q, fi in methods.items():
                w = _LockWalker(fi, all_lock_attrs, all_event_attrs)
                w.visit(fi.node)
                for a, b, ln in w.order_edges:
                    order_edges.setdefault((a, b), (fi.path, ln))
                fn_acquires[q] = set(w.acquired)
                fn_calls[q] = w.calls_with_locks
                for lineno, expr in w.waits:
                    if q in thread_reach:
                        reason = P.pragma_for(fi.module, lineno, PASS_ID)
                        findings.append(Finding(
                            PASS_ID, "LD003", SEV_WARN, fi.path, lineno,
                            f"`{expr}()` without a timeout in "
                            "thread-reachable code — an un-wakeable "
                            "park (a missed set() hangs the thread)",
                            f"{fi.qualname}:{expr}",
                            suppressed_by=reason))
                for attr, acc in w.accesses:
                    per_attr.setdefault(attr, []).append(acc)
            if not t_meths:
                continue
            for attr, accs in sorted(per_attr.items()):
                if (attr in ci.attr_ctors
                        and ci.attr_ctors[attr] in _SYNC_CTORS):
                    continue
                if attr.startswith("__"):
                    continue
                t_writes = [a for a in accs
                            if a.kind == "write"
                            and a.func.qualname in t_meths
                            and a.func.name != "__init__"]
                other = [a for a in accs
                         if a.func.qualname not in t_meths
                         and a.func.name != "__init__"]
                if not t_writes or not other:
                    continue
                unlocked_w = [a for a in t_writes if not a.locks]
                # common lock: every thread write AND every other-side
                # access hold at least one shared lock name
                def _common(side_a: List[_Access],
                            side_b: List[_Access]) -> bool:
                    sets_a = [set(x.locks) for x in side_a]
                    sets_b = [set(x.locks) for x in side_b]
                    if not sets_a or not sets_b:
                        return False
                    inter = set.intersection(*(sets_a + sets_b))
                    return bool(inter)
                if _common(t_writes, other):
                    continue
                if not unlocked_w:
                    # thread side always locked; other side not — still a
                    # torn read risk but much weaker: report on the first
                    # unlocked other-side access
                    first = min((a for a in other if not a.locks),
                                key=lambda a: a.lineno, default=None)
                    if first is None:
                        continue
                    w0 = t_writes[0]
                    reason = P.pragma_for(first.func.module,
                                          first.lineno, PASS_ID)
                    findings.append(Finding(
                        PASS_ID, "LD001", SEV_WARN, first.func.path,
                        first.lineno,
                        f"self.{attr} is written under a lock from "
                        f"thread code ({w0.func.name}:{w0.lineno}) but "
                        f"read without one in {first.func.name}",
                        f"{ci.name}.{attr}", suppressed_by=reason))
                    continue
                w0 = unlocked_w[0]
                o0 = other[0]
                reason = P.pragma_for(w0.func.module, w0.lineno, PASS_ID)
                findings.append(Finding(
                    PASS_ID, "LD001", SEV_ERROR, w0.func.path, w0.lineno,
                    f"self.{attr} written from thread-reachable "
                    f"{w0.func.name} (line {w0.lineno}) without a lock "
                    f"and accessed in {o0.func.name} (line {o0.lineno}) "
                    "— no common lock covers both sides",
                    f"{ci.name}.{attr}", suppressed_by=reason))

    # ---- plain functions: order edges + waits outside classes ------------
    for mod in proj.modules.values():
        for q, fi in mod.functions.items():
            if fi.cls is not None or q in fn_acquires:
                continue
            w = _LockWalker(fi, all_lock_attrs, all_event_attrs)
            w.visit(fi.node)
            for a, b, ln in w.order_edges:
                order_edges.setdefault((a, b), (fi.path, ln))
            fn_acquires[q] = set(w.acquired)
            fn_calls[q] = w.calls_with_locks
            for lineno, expr in w.waits:
                if q in thread_reach:
                    reason = P.pragma_for(fi.module, lineno, PASS_ID)
                    findings.append(Finding(
                        PASS_ID, "LD003", SEV_WARN, fi.path, lineno,
                        f"`{expr}()` without a timeout in "
                        "thread-reachable code — an un-wakeable park",
                        f"{fi.qualname}:{expr}",
                        suppressed_by=reason))

    # ---- one-level call propagation into the order graph -----------------
    for q, calls in fn_calls.items():
        fi = proj.functions.get(q)
        if fi is None:
            continue
        for chain, held, lineno in calls:
            if not held:
                continue
            for callee in proj.resolve_call(chain, fi):
                for lk in fn_acquires.get(callee.qualname, ()):
                    if lk != held[-1]:
                        order_edges.setdefault(
                            (held[-1], lk), (fi.path, lineno))

    # ---- cycle detection -------------------------------------------------
    graph: Dict[str, Set[str]] = {}
    for (a, b) in order_edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(node: str, stack: List[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt in stack:
                cyc = tuple(sorted(stack[stack.index(nxt):] + [nxt]))
                if cyc in seen_cycles:
                    continue
                seen_cycles.add(cyc)
                path, ln = order_edges[(node, nxt)]
                findings.append(Finding(
                    PASS_ID, "LD002", SEV_ERROR, path, ln,
                    "lock-acquisition-order cycle (deadlock candidate): "
                    + " -> ".join(stack[stack.index(nxt):] + [nxt]),
                    "cycle:" + ">".join(cyc)))
            elif len(stack) < 16:
                dfs(nxt, stack + [nxt])

    for start in sorted(graph):
        dfs(start, [start])
    return findings
