"""graftlint — the repo-native static-analysis suite.

Six PRs of runtime conventions (zero hot-loop syncs, bit-identical
replay, flag/faultpoint/metric registries mirrored in docs, lock
discipline across the threaded pipeline) become machine-checked
invariants: five AST passes over ``paddlebox_tpu/``, ``tools/`` and
``bench.py``, stdlib-only, no jax import, runs in tier-1.

    python -m tools.graftlint                  # human-readable, exit 1 on new
    python -m tools.graftlint --json           # findings as JSON
    python -m tools.graftlint --summary s.json # trend-tracking counts
    python -m tools.graftlint --write-baseline # adopt current findings

See STATIC_ANALYSIS.md for the pass catalog, pragma syntax and the
baseline workflow.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from tools.graftlint.config import Config, default_config, fixture_config
from tools.graftlint.findings import (Baseline, Finding, RunResult,
                                      SEV_ERROR, SEV_WARN)
from tools.graftlint.project import Project

__all__ = [
    "Config", "default_config", "fixture_config", "Baseline",
    "Finding", "RunResult", "Project", "run_passes", "SEV_ERROR",
    "SEV_WARN", "DEFAULT_BASELINE",
]

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def run_passes(cfg: Config,
               only: Optional[Sequence[str]] = None) -> RunResult:
    """Parse the tree once, run the (selected) passes, return findings
    with pragmas already applied — baseline application is the
    caller's move (CLI / tests decide which baseline file)."""
    from tools.graftlint.passes import ALL_PASSES
    proj = Project(cfg.root, cfg.roots, cfg.exclude)
    selected = list(only) if only else list(ALL_PASSES)
    unknown = [p for p in selected if p not in ALL_PASSES]
    if unknown:
        raise ValueError(f"unknown pass(es): {unknown}; "
                         f"available: {sorted(ALL_PASSES)}")
    findings = []
    for pid in selected:
        findings.extend(ALL_PASSES[pid](proj, cfg))
    findings.sort(key=lambda f: (f.path, f.lineno, f.code, f.key))
    return RunResult(findings, cfg.root,
                     files_scanned=len(proj.modules),
                     pass_ids=selected)
