"""Graftlint configuration.

Everything repo-specific lives here — the analyzed roots, the declared
hot-path and replay root sets, and the doc files the drift passes
cross-check — so the passes themselves stay generic (the test fixtures
run them against tiny synthetic projects with their own config).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class Config:
    root: str
    # analyzed file roots, relative to ``root``
    roots: Sequence[str] = ("paddlebox_tpu", "tools", "bench.py")
    exclude: Sequence[str] = ()
    # -- pass 1: hot-path sync detector -----------------------------------
    # Functions whose transitive callees must not sync the host: the
    # jitted step builders (a sync there is a tracer error waiting for a
    # shape change), the dispatch loop, the prefetch producer, the
    # lookup exchange, and every Pallas kernel caller.
    hot_roots: Sequence[str] = (
        "paddlebox_tpu.train.ctr_trainer:CTRTrainer._build_step",
        "paddlebox_tpu.train.ctr_trainer:CTRTrainer._build_eval_step",
        "paddlebox_tpu.train.ctr_trainer:CTRTrainer.train_pass",
        "paddlebox_tpu.train.ctr_trainer:CTRTrainer.eval_pass",
        "paddlebox_tpu.train.ctr_trainer:CTRTrainer._prefetch_batches",
        "paddlebox_tpu.embedding.lookup:compute_bucketing",
        "paddlebox_tpu.embedding.lookup:pull_local",
        "paddlebox_tpu.embedding.lookup:push_local",
        "paddlebox_tpu.ops.pallas_kernels.sorted_gather:*",
        "paddlebox_tpu.ops.pallas_kernels.sorted_scatter:*",
        "paddlebox_tpu.ops.pallas_kernels.flash_attention:*",
        "paddlebox_tpu.ops.pallas_kernels.seqpool_cvm:*",
    )
    # attribute-call suffixes treated as producing device values
    # (compiled-step handles: self._step_fn(...), self._mega_fn(...))
    device_fn_suffixes: Sequence[str] = ("_fn",)
    # function names whose NESTED defs are jit-traced bodies: every
    # parameter of those defs is a tracer (device value)
    traced_parents: Sequence[str] = ("_build_step", "_build_eval_step")
    # -- pass 2: flag hygiene ---------------------------------------------
    flags_module: str = "paddlebox_tpu/core/flags.py"
    # docs where every defined flag must appear as FLAGS_<name>
    flag_docs: Sequence[str] = ("README.md", "OBSERVABILITY.md",
                                "ROBUSTNESS.md")
    # -- pass 3: registry drift -------------------------------------------
    robustness_doc: str = "ROBUSTNESS.md"
    faultpoint_section: str = "Faultpoint site table"
    metric_docs: Sequence[str] = ("OBSERVABILITY.md", "ROBUSTNESS.md")
    # -- pass 5: replay purity --------------------------------------------
    replay_roots: Sequence[str] = (
        "paddlebox_tpu.train.day_runner:DayRunner.train_pass",
        "paddlebox_tpu.embedding.pass_engine:PassEngine.*",
        "paddlebox_tpu.embedding.device_store:*",
        # The streaming pass loop replays carved manifests bit-identical
        # after kill -9: its clock is INJECTED (clock=), so wall reads
        # on the closure would be a contract break, not telemetry.
        "paddlebox_tpu.stream.runner:StreamRunner.*",
        "paddlebox_tpu.stream.source:*",
        # The fleet trace generator replays seeded traces bit-identical
        # (the autopilot drill's determinism contract): its RNG and
        # clock are injected — wall time or a global draw would make
        # two replays of one config disagree.
        "paddlebox_tpu.serving.traceload:*",
    )
    # suppression
    baseline_path: Optional[str] = None   # default: <pkg>/baseline.json

    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel)


def default_config(root: str) -> Config:
    return Config(root=os.path.abspath(root))


def fixture_config(root: str, **overrides) -> Config:
    """Config for a synthetic test project: analyze everything under
    ``root`` and let the test override the root sets / doc paths."""
    cfg = Config(root=os.path.abspath(root), roots=("",),
                 hot_roots=(), replay_roots=(),
                 flags_module="flags.py",
                 flag_docs=("DOCS.md",),
                 robustness_doc="DOCS.md",
                 metric_docs=("DOCS.md",))
    return dataclasses.replace(cfg, **overrides)
