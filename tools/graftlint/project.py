"""The shared project walker behind every graftlint pass.

One parse of the tree (stdlib ``ast`` only — no jax, no runtime import)
produces the three structures the passes share:

- a **module index**: every ``.py`` file under the configured roots,
  parsed, with its import table and top-level symbols;
- an **intra-project call graph**: best-effort resolution of every call
  site to project functions/methods (local names, ``from``-imports,
  ``module.func``, ``self.method`` through the enclosing class and its
  project bases, plus a unique-name fallback for ``obj.method`` when
  exactly one project function carries that name);
- a **string-literal registry**: every literal (and f-string pattern)
  passed to the flag / faultpoint / metric APIs, with file:line, so the
  drift passes cross-check code against the markdown tables without
  executing anything.

Resolution is deliberately *recall-biased*: hot-path reachability wants
to over-approximate (a missed edge hides a sync; a spurious edge at
worst asks for a pragma). Passes that need precision (lock discipline)
re-walk function bodies themselves with the graph as scaffolding.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# --------------------------------------------------------------------------
# Data model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class FunctionInfo:
    """One function or method (nested defs included)."""
    qualname: str                 # "pkg.mod:Class.method" / "pkg.mod:f.inner"
    module: "ModuleInfo"
    node: ast.AST                 # FunctionDef | AsyncFunctionDef | Lambda
    cls: Optional[str]            # enclosing class name, if a method
    parent: Optional[str]         # enclosing function qualname, if nested
    name: str = ""

    def __post_init__(self):
        self.name = getattr(self.node, "name",
                            self.qualname.rsplit(".", 1)[-1])

    @property
    def lineno(self) -> int:
        return self.node.lineno

    @property
    def path(self) -> str:
        return self.module.path


@dataclasses.dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    bases: List[str]
    methods: Dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    # attribute name -> the rhs call chain it was assigned from in any
    # method body (e.g. "_lock" -> "threading.Lock"); first writer wins.
    attr_ctors: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class StringRef:
    """A string literal (or f-string pattern) at a registry call site."""
    value: str                    # literal text; f-string parts become "*"
    api: str                      # e.g. "monitor.add", "faultpoint", "flag"
    path: str
    lineno: int
    is_pattern: bool = False      # True when built from an f-string


class ModuleInfo:
    def __init__(self, name: str, path: str, tree: ast.Module,
                 source: str):
        self.name = name                      # dotted, e.g. "pkg.train.x"
        self.path = path
        self.tree = tree
        self.source = source
        self.lines = source.splitlines()
        # alias -> dotted module ("np" -> "numpy"); from-import:
        # name -> (module, original_name)
        self.import_modules: Dict[str, str] = {}
        self.import_names: Dict[str, Tuple[str, str]] = {}
        self.functions: Dict[str, FunctionInfo] = {}   # qual -> info
        self.classes: Dict[str, ClassInfo] = {}

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


# --------------------------------------------------------------------------
# AST helpers
# --------------------------------------------------------------------------


def call_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """``a.b.c`` -> ("a","b","c"); bare name -> ("a",); else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_pattern(node: ast.AST) -> Optional[str]:
    """JoinedStr -> glob pattern with '*' for each formatted value."""
    if not isinstance(node, ast.JoinedStr):
        return None
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("*")
    return "".join(parts)


def string_or_pattern(node: ast.AST) -> Optional[Tuple[str, bool]]:
    s = literal_str(node)
    if s is not None:
        return s, False
    p = fstring_pattern(node)
    if p is not None:
        return p, True
    return None


# --------------------------------------------------------------------------
# Pragmas
# --------------------------------------------------------------------------

# ``# graftlint: allow-sync(reason)`` — also allow-flag / allow-registry /
# allow-lock / allow-replay, and the catch-all allow(reason). A pragma on
# the finding's line or the line directly above suppresses it.
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*allow(?:-(?P<kind>[a-z_]+))?\s*\(\s*(?P<reason>[^)]*)\)")

PRAGMA_KINDS = {
    "sync": "hot_sync",
    "flag": "flag_hygiene",
    "registry": "registry_drift",
    "lock": "lock_discipline",
    "replay": "replay_purity",
}


def pragma_for(module: ModuleInfo, lineno: int,
               pass_id: str) -> Optional[str]:
    """Return the pragma reason suppressing ``pass_id`` at ``lineno``
    (same line or the line above), or None."""
    for ln in (lineno, lineno - 1):
        m = _PRAGMA_RE.search(module.line(ln))
        if not m:
            continue
        kind = m.group("kind")
        if kind is None or PRAGMA_KINDS.get(kind) == pass_id:
            return m.group("reason").strip() or "allowed by pragma"
    return None


# --------------------------------------------------------------------------
# The project
# --------------------------------------------------------------------------


class Project:
    """Parsed view of the tree. Build once, share across passes."""

    def __init__(self, root: str, roots: Sequence[str],
                 exclude: Sequence[str] = ()):
        self.root = os.path.abspath(root)
        self.modules: Dict[str, ModuleInfo] = {}      # dotted name -> info
        self.functions: Dict[str, FunctionInfo] = {}  # qualname -> info
        self.classes: Dict[str, List[ClassInfo]] = {}  # bare name -> infos
        self.parse_errors: List[Tuple[str, str]] = []
        # simple-name -> [qualnames] for the unique-name fallback
        self._by_name: Dict[str, List[str]] = {}
        self._call_cache: Dict[str, Tuple[str, ...]] = {}
        for path in self._iter_paths(roots, exclude):
            self._load(path)
        self._index()

    # -- loading -----------------------------------------------------------

    def _iter_paths(self, roots: Sequence[str],
                    exclude: Sequence[str]) -> Iterable[str]:
        exc = [os.path.normpath(e) for e in exclude]
        for r in roots:
            full = os.path.join(self.root, r)
            if os.path.isfile(full):
                yield full
                continue
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                rel_dir = os.path.relpath(dirpath, self.root)
                if any(rel_dir == e or rel_dir.startswith(e + os.sep)
                       for e in exc):
                    dirnames[:] = []
                    continue
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)

    def _module_name(self, path: str) -> str:
        rel = os.path.relpath(path, self.root)
        rel = rel[:-3] if rel.endswith(".py") else rel
        parts = rel.split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1] or parts
        return ".".join(parts)

    def _load(self, path: str) -> None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError) as e:
            self.parse_errors.append((path, str(e)))
            return
        mod = ModuleInfo(self._module_name(path), path, tree, src)
        self.modules[mod.name] = mod
        self._collect(mod)

    # -- symbol collection -------------------------------------------------

    def _collect(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.import_modules[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    mod.import_names[a.asname or a.name] = (
                        node.module, a.name)

        def walk_body(body, cls: Optional[ClassInfo], prefix: str,
                      parent: Optional[str]):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{mod.name}:{prefix}{node.name}"
                    fi = FunctionInfo(qual, mod, node,
                                      cls.name if cls else None, parent)
                    mod.functions[qual] = fi
                    if cls is not None and parent is None:
                        cls.methods[node.name] = fi
                    walk_body(node.body, cls, prefix + node.name + ".",
                              qual)
                elif isinstance(node, ast.ClassDef):
                    ci = ClassInfo(node.name, mod, node,
                                   [b.id for b in node.bases
                                    if isinstance(b, ast.Name)]
                                   + [b.attr for b in node.bases
                                      if isinstance(b, ast.Attribute)])
                    mod.classes[node.name] = ci
                    walk_body(node.body, ci, prefix + node.name + ".",
                              parent)
                    self._collect_attr_ctors(ci)
                elif isinstance(node, (ast.If, ast.Try, ast.With,
                                       ast.For, ast.While)):
                    # conservative: walk nested statement bodies for defs
                    for field in ("body", "orelse", "finalbody"):
                        walk_body(getattr(node, field, []) or [],
                                  cls, prefix, parent)
                    for h in getattr(node, "handlers", []) or []:
                        walk_body(h.body, cls, prefix, parent)

        walk_body(mod.tree.body, None, "", None)

    def _collect_attr_ctors(self, ci: ClassInfo) -> None:
        """``self.x = threading.Lock()`` anywhere in the class body."""
        for node in ast.walk(ci.node):
            if not isinstance(node, ast.Assign) or not isinstance(
                    node.value, ast.Call):
                continue
            chain = call_chain(node.value.func)
            if chain is None:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and t.attr not in ci.attr_ctors):
                    ci.attr_ctors[t.attr] = ".".join(chain)

    def _index(self) -> None:
        for mod in self.modules.values():
            for qual, fi in mod.functions.items():
                self.functions[qual] = fi
                self._by_name.setdefault(fi.name, []).append(qual)
            for name, ci in mod.classes.items():
                self.classes.setdefault(name, []).append(ci)

    # -- call resolution ---------------------------------------------------

    def class_method(self, cls_name: str, meth: str,
                     seen: Optional[Set[str]] = None
                     ) -> Optional[FunctionInfo]:
        seen = seen or set()
        if cls_name in seen:
            return None
        seen.add(cls_name)
        for ci in self.classes.get(cls_name, []):
            if meth in ci.methods:
                return ci.methods[meth]
            for b in ci.bases:
                got = self.class_method(b, meth, seen)
                if got is not None:
                    return got
        return None

    def resolve_call(self, chain: Tuple[str, ...],
                     caller: FunctionInfo) -> List[FunctionInfo]:
        """Best-effort: call chain at a site inside ``caller`` -> project
        functions it may invoke."""
        mod = caller.module
        out: List[FunctionInfo] = []
        if len(chain) == 1:
            name = chain[0]
            # nested / sibling function in the same scope chain
            for pref in self._scope_prefixes(caller):
                fi = mod.functions.get(f"{mod.name}:{pref}{name}")
                if fi is not None:
                    return [fi]
            if name in mod.import_names:
                src_mod, src_name = mod.import_names[name]
                fi = self.functions.get(f"{src_mod}:{src_name}")
                if fi is not None:
                    return [fi]
                # from X import Class — calling it runs __init__
                got = self.class_method_in(src_mod, src_name, "__init__")
                if got is not None:
                    return [got]
            return out
        head, rest = chain[0], chain[1:]
        if head == "self" and caller.cls is not None and len(rest) == 1:
            got = self.class_method(caller.cls, rest[0])
            if got is not None:
                return [got]
        if head in mod.import_modules and len(rest) == 1:
            target = mod.import_modules[head]
            fi = self.functions.get(f"{target}:{rest[0]}")
            if fi is not None:
                return [fi]
            if target not in self.modules:
                return out  # external library — never unique-name it
        if head in mod.import_names and len(rest) == 1:
            src_mod, src_name = mod.import_names[head]
            fi = self.functions.get(f"{src_mod}:{src_name}.{rest[0]}")
            if fi is not None:
                return [fi]
            got = self.class_method_in(src_mod, src_name, rest[0])
            if got is not None:
                return [got]
        # unique-name fallback on the final attribute: obj.method(...)
        quals = self._by_name.get(chain[-1], [])
        if len(quals) == 1:
            return [self.functions[quals[0]]]
        return out

    def class_method_in(self, mod_name: str, cls_name: str,
                        meth: str) -> Optional[FunctionInfo]:
        mod = self.modules.get(mod_name)
        if mod is None:
            return None
        ci = mod.classes.get(cls_name)
        if ci is None:
            return None
        if meth in ci.methods:
            return ci.methods[meth]
        for b in ci.bases:
            got = self.class_method(b, meth)
            if got is not None:
                return got
        return None

    def _scope_prefixes(self, fi: FunctionInfo) -> List[str]:
        """Qual prefixes to try for a bare-name call inside ``fi``:
        its own nested scope, enclosing scopes, then module level."""
        local = fi.qualname.split(":", 1)[1]
        parts = local.split(".")
        prefixes = []
        for i in range(len(parts), 0, -1):
            prefixes.append(".".join(parts[:i]) + ".")
        prefixes.append("")
        # a method's bare-name calls also see module scope (captured by
        # the trailing ""), not the class namespace — python semantics.
        return prefixes

    def callees(self, fi: FunctionInfo) -> List[FunctionInfo]:
        cached = self._call_cache.get(fi.qualname)
        if cached is not None:
            return [self.functions[q] for q in cached
                    if q in self.functions]
        out: List[FunctionInfo] = []
        seen: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                chain = call_chain(node.func)
                if chain is None:
                    continue
                for target in self.resolve_call(chain, fi):
                    if target.qualname not in seen:
                        seen.add(target.qualname)
                        out.append(target)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                if node is fi.node:
                    continue
                # a nested def is conservatively reachable from its parent
                qual = self._nested_qual(fi, node)
                if qual and qual not in seen:
                    seen.add(qual)
                    out.append(self.functions[qual])
        self._call_cache[fi.qualname] = tuple(seen)
        return out

    def _nested_qual(self, parent: FunctionInfo,
                     node: ast.AST) -> Optional[str]:
        for qual, fi in parent.module.functions.items():
            if fi.node is node:
                return qual
        return None

    def reachable(self, root_specs: Sequence[str]) -> Dict[str, int]:
        """Transitive closure from root specs.

        A spec is ``module:qual`` (exact), ``module:Class.*`` (all
        methods), or ``module:*`` (every function in the module).
        Returns {qualname: depth}; depth 0 = root.
        """
        frontier: List[FunctionInfo] = []
        for spec in root_specs:
            frontier.extend(self._match_spec(spec))
        depth: Dict[str, int] = {f.qualname: 0 for f in frontier}
        work = list(frontier)
        while work:
            fi = work.pop()
            d = depth[fi.qualname]
            for callee in self.callees(fi):
                if callee.qualname not in depth:
                    depth[callee.qualname] = d + 1
                    work.append(callee)
        return depth

    def _match_spec(self, spec: str) -> List[FunctionInfo]:
        mod_name, _, qual = spec.partition(":")
        mod = self.modules.get(mod_name)
        if mod is None:
            return []
        if qual == "*":
            return [fi for fi in mod.functions.values()
                    if fi.parent is None]
        if qual.endswith(".*"):
            prefix = qual[:-1]           # keep the trailing dot
            return [fi for q, fi in mod.functions.items()
                    if q.split(":", 1)[1].startswith(prefix)
                    and "." not in q.split(":", 1)[1][len(prefix):]]
        fi = mod.functions.get(f"{mod_name}:{qual}")
        return [fi] if fi is not None else []

    # -- string-literal registry ------------------------------------------

    def string_refs(self, apis: Dict[str, int]) -> List[StringRef]:
        """Collect literal/f-string args at registry call sites.

        ``apis`` maps an API tail (the call chain's last 1–2 elements
        joined with '.') to the positional arg index holding the name,
        e.g. {"monitor.add": 0, "faultpoint": 0, "flags.flag": 0}.
        """
        out: List[StringRef] = []
        for mod in self.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = call_chain(node.func)
                if chain is None:
                    continue
                for tail_len in (2, 1):
                    if len(chain) < tail_len:
                        continue
                    tail = ".".join(chain[-tail_len:])
                    if tail not in apis:
                        continue
                    idx = apis[tail]
                    if idx >= len(node.args):
                        continue
                    got = string_or_pattern(node.args[idx])
                    if got is not None:
                        val, is_pat = got
                        out.append(StringRef(val, tail, mod.path,
                                             node.lineno, is_pat))
                    break
        return out


# --------------------------------------------------------------------------
# Markdown helpers (doc-side of the drift passes)
# --------------------------------------------------------------------------

_BACKTICK_RE = re.compile(r"`([^`]+)`")
_FENCE_RE = re.compile(r"^```.*?^```[ \t]*$", re.M | re.S)


def read_doc(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()
    except OSError:
        return ""


def backtick_tokens(text: str) -> List[str]:
    """Inline-code tokens. Fenced blocks are dropped first (their
    backtick runs would flip pairing parity), and a token wrapped
    across a line break (markdown reflow) is rejoined without the
    break/indent."""
    text = _FENCE_RE.sub("", text)
    out = []
    for tok in _BACKTICK_RE.findall(text):
        if "\n" in tok:
            tok = re.sub(r"\s*\n\s*", "", tok)
        out.append(tok)
    return out


def doc_section(text: str, heading: str) -> str:
    """The body of the markdown section whose heading contains
    ``heading`` (case-insensitive), up to the next same-or-higher-level
    heading. Empty string when absent."""
    lines = text.splitlines()
    out: List[str] = []
    level = None
    for ln in lines:
        m = re.match(r"(#+)\s+(.*)", ln)
        if m:
            if level is not None and len(m.group(1)) <= level:
                break
            if level is None and heading.lower() in m.group(2).lower():
                level = len(m.group(1))
                continue
        if level is not None:
            out.append(ln)
    return "\n".join(out)


def expand_doc_pattern(tok: str) -> List[str]:
    """A backticked doc token -> glob patterns.

    ``pass/{train,eval}_*`` -> ["pass/train_*", "pass/eval_*"];
    ``fault/<site>_injected`` -> ["fault/*_injected"]; ``...`` -> "*".
    """
    tok = tok.strip()
    tok = re.sub(r"<[^>]*>", "*", tok)
    tok = tok.replace("...", "*")
    m = re.search(r"\{([^{}]*)\}", tok)
    if m:
        alts = [a.strip() for a in m.group(1).split(",")]
        out = []
        for a in alts:
            out.extend(expand_doc_pattern(
                tok[:m.start()] + a + tok[m.end():]))
        return out
    return [tok]
