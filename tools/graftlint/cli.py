"""``python -m tools.graftlint`` — the CLI.

Exit codes: 0 = clean (no failing findings under --fail-on), 1 =
findings failed the gate, 2 = usage / internal error. Pure stdlib, no
jax — milliseconds over the full tree, safe anywhere (CI, pre-commit,
the tier-1 suite via tests/test_graftlint.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from tools.graftlint import (Baseline, DEFAULT_BASELINE, default_config,
                             run_passes)
from tools.graftlint.config import Config


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="repo-native static analysis: hot-path sync, flag "
                    "hygiene, registry drift, lock discipline, replay "
                    "purity (see STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="roots to analyze, relative to --root "
                         "(default: paddlebox_tpu tools bench.py)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the parent of tools/)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON on stdout")
    ap.add_argument("--summary", metavar="PATH",
                    help="write the trend-tracking summary JSON "
                         "(findings_total / baselined / new / per-pass) "
                         "— feed it to tools/perf_gate.py")
    ap.add_argument("--baseline", metavar="PATH", default=None,
                    help=f"suppression baseline (default: "
                         f"{os.path.relpath(DEFAULT_BASELINE)})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings into the baseline "
                         "(keeps existing reasons) and exit 0")
    ap.add_argument("--fail-on", choices=("new", "any", "none"),
                    default="new",
                    help="what fails the run: 'new' (default — "
                         "non-baselined errors), 'any' (every error, "
                         "baselined or not), 'none' (report only)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass ids to run")
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    cfg = default_config(root)
    if args.paths:
        cfg = Config(root=cfg.root, roots=tuple(args.paths))
    only = args.passes.split(",") if args.passes else None

    try:
        result = run_passes(cfg, only)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or DEFAULT_BASELINE
    baseline = Baseline.load(baseline_path)

    if args.write_baseline:
        entries = {}
        for f in result.active:
            fp = f.fingerprint(result.root)
            entries[fp] = baseline.entries.get(
                fp, "baselined at adoption — REVIEW AND REPLACE with a "
                    "real reason (STATIC_ANALYSIS.md)")
        Baseline(entries).save(baseline_path)
        print(f"graftlint: wrote {len(entries)} baseline entries to "
              f"{baseline_path}")
        return 0

    result.apply_baseline(baseline)
    failures = result.failures(args.fail_on)
    summary = result.summary()

    if args.summary:
        with open(args.summary, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2)
            f.write("\n")

    if args.json:
        print(json.dumps({
            "summary": summary,
            "findings": [f.to_dict(result.root) for f in result.findings],
        }, indent=2))
    else:
        for f in sorted(result.new, key=lambda f: (f.path, f.lineno)):
            rel = os.path.relpath(f.path, result.root)
            print(f"{rel}:{f.lineno}: [{f.pass_id}/{f.code}] "
                  f"{f.severity}: {f.message}")
        print(f"graftlint: {summary['findings_total']} findings "
              f"({summary['new']} new, {summary['baselined']} baselined, "
              f"{summary['allowed']} pragma-allowed) over "
              f"{summary['files_scanned']} files")
    if failures:
        print(f"graftlint: FAILED — {len(failures)} finding(s) not "
              f"covered by {os.path.relpath(baseline_path)} "
              "(fix, pragma with a reason, or --write-baseline)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
