"""AOT-compile the FULL jitted CTR train step for TPU — no TPU needed.

The per-kernel AOT tests (tests/test_pallas_aot.py) prove each Pallas
kernel compiles; this tool proves the whole bench device program does —
pull all-to-all, fwd/bwd, scatter-accumulate push (Pallas path active:
the flag's "auto" gate is forced on), dense update, AUC histograms —
through the real XLA:TPU + Mosaic pipeline via jax's compile-only PJRT
topology. Run after any change to the step, kernels, or models:

    python tools/aot_check_step.py

Shapes are a scaled-down bench config (full-scale kernel shapes are
covered by the per-kernel tests; program structure, not size, is what
this validates).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Append (last occurrence of a repeated flag wins) so an inherited
# 8-virtual-device setting from a test env doesn't leak in. 4 virtual
# CPU devices: the single-chip step builds on devices[:1]; the ZeRO
# dp=4 section needs a real 4-way mesh to learn its argument structure.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from paddlebox_tpu.core import flags as flagmod  # noqa: E402
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf  # noqa: E402
from paddlebox_tpu.embedding import TableConfig  # noqa: E402
from paddlebox_tpu.models import DeepFM  # noqa: E402
from paddlebox_tpu.parallel import HybridTopology, build_mesh  # noqa: E402
from paddlebox_tpu.train import CTRTrainer, TrainerConfig  # noqa: E402

from tools._aot_common import sds as sds_like  # noqa: E402


def main() -> None:
    n_slots, emb_dim, dense_dim, batch = 8, 16, 13, 1024
    pass_keys = 200_000

    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(n_slots))
    slots += (SlotConf("d", is_dense=True, dim=dense_dim),)
    feed = DataFeedConfig(slots=slots, batch_size=batch,
                          slot_capacity_slack=1.0)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(n_slots)),
                   emb_dim=emb_dim, dense_dim=dense_dim,
                   hidden=(400, 400, 400))
    mesh_cpu = build_mesh(HybridTopology(dp=1), devices=jax.devices()[:1])
    tr = CTRTrainer(model, feed,
                    TableConfig(dim=emb_dim, learning_rate=0.05),
                    mesh=mesh_cpu,
                    config=TrainerConfig(auc_num_buckets=1 << 16,
                                         compute_dtype="bfloat16",
                                         data_norm=True))
    tr.init(seed=0)

    # Real pass state on CPU to learn the exact argument structure.
    rng = np.random.default_rng(0)
    keys = np.sort(rng.choice(np.arange(1, 10 * pass_keys, dtype=np.uint64),
                              pass_keys, replace=False))
    tr.engine.feed_pass([keys for _ in tr.engine.groups])
    tables = tr.engine.begin_pass()

    import ml_dtypes
    from paddlebox_tpu.data.slots import SlotBatch
    ids = {f"s{i}": rng.choice(keys, batch).astype(np.uint64)
           for i in range(n_slots)}
    segs = {n: np.arange(batch, dtype=np.int32) for n in ids}
    batch_obj = SlotBatch(
        labels=(rng.random((batch, 1)) < 0.2).astype(np.float32),
        valid=np.ones((batch,), bool),
        ids=ids, segments=segs,
        lengths={n: np.ones((batch,), np.int32) for n in ids},
        dense={"d": rng.normal(size=(batch, dense_dim)
                               ).astype(np.float32)})
    rows = tr._map_batch_rows(batch_obj)
    segs_j = {n: jnp.asarray(batch_obj.segments[n]) for n in ids}
    dense_j = jnp.asarray(batch_obj.dense["d"].astype(ml_dtypes.bfloat16))

    args = (tables, tr.params, tr.opt_state, tr.auc_state, rows, segs_j,
            jnp.asarray(batch_obj.labels), jnp.asarray(batch_obj.valid),
            dense_j, jnp.zeros((), jnp.int32))

    # Rebuild the step against a compile-only TPU device mesh and force
    # the Pallas scatter path (the "auto" gate keys off the default
    # backend, which is cpu here).
    try:
        topo = topologies.get_topology_desc("v5e:2x2x1", "tpu")
    except Exception as e:  # noqa: BLE001 - any init failure means no AOT
        # Sentinel for CI: environments without libtpu's AOT topology
        # (matched by tests/test_aot_step.py to SKIP, not fail).
        print(f"TPU-AOT-TOPOLOGY-UNAVAILABLE: {e!r}")
        return
    tr.mesh = Mesh(np.array([topo.devices[0]]), (tr.axis,))
    flagmod.set_flags({"sparse_scatter_kernel": "pallas",
                       "sparse_gather_kernel": "pallas"})
    step = tr._build_step()
    compiled = step.lower(*sds_like(args)).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # jax < 0.5 returns [dict]
        ca = ca[0] if ca else {}
    print("FULL-STEP TPU AOT COMPILE: OK "
          f"(flops={ca.get('flops', 0):.3e})")

    # int8 dense-sync variant (FLAGS_dense_allreduce_dtype=int8): the
    # quantize -> psum(int32) -> dequantize dense-grad wire is a
    # different device program than the verbatim-f32 step — it must
    # survive XLA:TPU on its own.
    flagmod.set_flags({"dense_allreduce_dtype": "int8"})
    try:
        tr._build_step().lower(*sds_like(args)).compile()
    finally:
        flagmod.set_flags({"dense_allreduce_dtype": "f32"})
    print("FULL-STEP(int8 dense sync) TPU AOT COMPILE: OK")

    eval_step = tr._build_eval_step()
    eval_args = (tables, tr.params, tr.auc_state, rows, segs_j,
                 jnp.asarray(batch_obj.labels),
                 jnp.asarray(batch_obj.valid), dense_j)
    eval_step.lower(*sds_like(eval_args)).compile()
    print("EVAL-STEP TPU AOT COMPILE: OK")

    # K-step scanned megastep (FLAGS_trainer_steps_per_dispatch=4):
    # the lax.scan wrapper + donation + both Pallas kernels INSIDE the
    # scan body must survive the real XLA:TPU + Mosaic pipeline —
    # compile-only shape stand-ins with the stacked [K, ...] leading
    # axis the prefetcher produces.
    K = 4

    def stk(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                (K,) + tuple(np.shape(x)), jnp.asarray(x).dtype), tree)

    i32 = jax.ShapeDtypeStruct((), jnp.int32)
    mega = tr._build_step(k_steps=K)
    mega_args = (*sds_like((tables, tr.params, tr.opt_state,
                            tr.auc_state)), i32, i32,
                 stk(rows), stk(segs_j), stk(batch_obj.labels),
                 stk(batch_obj.valid), stk(dense_j))
    mega.lower(*mega_args).compile()
    print(f"MEGASTEP(K={K}) TPU AOT COMPILE: OK")

    mega_eval = tr._build_eval_step(k_steps=K)
    mega_eval_args = (*sds_like((tables, tr.params, tr.auc_state)), i32,
                      stk(rows), stk(segs_j), stk(batch_obj.labels),
                      stk(batch_obj.valid), stk(dense_j))
    mega_eval.lower(*mega_eval_args).compile()
    print(f"MEGASTEP-EVAL(K={K}) TPU AOT COMPILE: OK")

    # Fused pass-boundary program (FLAGS_pass_boundary_fuse): the
    # end_pass scatter + next-pass remainder gather in ONE dispatch —
    # both the single-chip program and the sharded all_to_all variant
    # must survive XLA:TPU (the boundary is pure-XLA scatter/gather, so
    # any regression here is an XLA-lowering one, caught tunnel-free).
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddlebox_tpu.embedding.device_store import (
        _fused_boundary_fn_local, _fused_boundary_fn_sharded)

    w_rec = 2 * emb_dim + 8          # bench-ish fused record width
    rps = 32768                      # 20K-key pass pow2 bucket
    m_cap = 16384                    # shared-remainder pow2 bucket
    store_rows = 1 << 20
    mesh1 = Mesh(np.array([topo.devices[0]]), (tr.axis,))
    rep = NamedSharding(mesh1, P())

    def sd(shape, dt=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dt, sharding=rep)

    fb = _fused_boundary_fn_local((w_rec,), rps, rps)
    fb.lower((sd((store_rows + 1, w_rec)),), sd((rps + 1, w_rec)),
             sd((rps,), jnp.int32), sd((rps + 1, w_rec)),
             sd((m_cap,), jnp.int32), sd((m_cap,), jnp.int32)).compile()
    print("FUSED-BOUNDARY(local) TPU AOT COMPILE: OK")

    s = min(4, len(topo.devices))
    mesh_s = Mesh(np.array(topo.devices[:s]), (tr.axis,))
    cap = 2048
    scap = 1 << 18
    fbs = _fused_boundary_fn_sharded(mesh_s, tr.axis, s, cap, cap,
                                     (w_rec,), rps, rps, scap)
    f32, i32t = jnp.float32, jnp.int32
    fbs.lower(
        (jax.ShapeDtypeStruct((s * (scap + 1), w_rec), f32),),
        jax.ShapeDtypeStruct((s * (rps + 1), w_rec), f32),
        jax.ShapeDtypeStruct((s, s * cap), i32t),
        jax.ShapeDtypeStruct((s, s * cap), i32t),
        jax.ShapeDtypeStruct((s * (rps + 1), w_rec), f32),
        jax.ShapeDtypeStruct((s, s * cap), i32t),
        jax.ShapeDtypeStruct((s, s * cap), i32t)).compile()
    print(f"FUSED-BOUNDARY(sharded S={s}) TPU AOT COMPILE: OK")

    # Split slot placement (FLAGS_table_slot_placement=split|host): the
    # resident store is a (hot [rows, D+3], slot [rows, Ke+Kw]) parts
    # tuple and the push writes BOTH parts inside one dispatch — the
    # column-split scatter and the two-part fused boundary are distinct
    # device programs from the 1-tuple fused layout and must survive
    # XLA:TPU on their own (same collective count: ONE request
    # all_to_all + ONE fused-width reply).
    from paddlebox_tpu.embedding.device_store import _scatter_fn_sharded
    hot_w = emb_dim + 3
    widths2 = (hot_w, w_rec - hot_w)
    parts2 = tuple(jax.ShapeDtypeStruct((s * (scap + 1), wp), f32)
                   for wp in widths2)
    _scatter_fn_sharded(mesh_s, tr.axis, s, cap, widths2).lower(
        parts2,
        jax.ShapeDtypeStruct((s * (rps + 1), w_rec), f32),
        jax.ShapeDtypeStruct((s, s * cap), i32t),
        jax.ShapeDtypeStruct((s, s * cap), i32t)).compile()
    fbs2 = _fused_boundary_fn_sharded(mesh_s, tr.axis, s, cap, cap,
                                      widths2, rps, rps, scap)
    fbs2.lower(
        parts2,
        jax.ShapeDtypeStruct((s * (rps + 1), w_rec), f32),
        jax.ShapeDtypeStruct((s, s * cap), i32t),
        jax.ShapeDtypeStruct((s, s * cap), i32t),
        jax.ShapeDtypeStruct((s * (rps + 1), w_rec), f32),
        jax.ShapeDtypeStruct((s, s * cap), i32t),
        jax.ShapeDtypeStruct((s, s * cap), i32t)).compile()
    print(f"SPLIT-SLOT-PUSH(sharded S={s}) TPU AOT COMPILE: OK")

    # ZeRO-sharded dense step (FLAGS_dense_zero=shard over dp=4): the
    # psum -> zero_slice -> shard update -> tiled all-gather schedule
    # plus the clip-decomposed optimizer, inside the full shard_map'd
    # CTR step with sharded opt_state in/out specs.
    check_zero_step(topo)


def check_zero_step(topo) -> None:
    from paddlebox_tpu.data.slots import SlotBatch

    flagmod.set_flags({"dense_zero": "shard", "dense_zero_min_size": 0})
    try:
        n_slots, emb_dim, batch = 4, 8, 256
        slots = tuple(SlotConf(f"s{i}", avg_len=1.0)
                      for i in range(n_slots))
        feed = DataFeedConfig(slots=slots, batch_size=batch,
                              slot_capacity_slack=1.0)
        model = DeepFM(slot_names=tuple(f"s{i}" for i in range(n_slots)),
                       emb_dim=emb_dim, hidden=(64,))
        tr = CTRTrainer(
            model, feed, TableConfig(dim=emb_dim),
            mesh=build_mesh(HybridTopology(dp=4)),
            config=TrainerConfig(auc_num_buckets=1 << 12,
                                 dense_optimizer="adam",
                                 grad_clip_norm=1.0))
        tr.init(seed=0)
        rng = np.random.default_rng(0)
        keys = np.sort(rng.choice(np.arange(1, 100_000, dtype=np.uint64),
                                  20_000, replace=False))
        tr.engine.feed_pass([keys for _ in tr.engine.groups])
        tables = tr.engine.begin_pass()
        ids = {f"s{i}": rng.choice(keys, batch).astype(np.uint64)
               for i in range(n_slots)}
        b = SlotBatch(
            labels=(rng.random((batch, 1)) < 0.2).astype(np.float32),
            valid=np.ones((batch,), bool), ids=ids,
            segments={n: np.arange(batch, dtype=np.int32) for n in ids},
            lengths={n: np.ones((batch,), np.int32) for n in ids},
            dense={})
        rows = tr._map_batch_rows(b)
        segs_j = {n: jnp.asarray(b.segments[n]) for n in ids}
        args = (tables, tr.params, tr.opt_state, tr.auc_state, rows,
                segs_j, jnp.asarray(b.labels), jnp.asarray(b.valid),
                jnp.zeros((batch, 0), jnp.float32),
                jnp.zeros((), jnp.int32))
        assert tr._dense_zero == "shard"
        tr.mesh = Mesh(np.array(topo.devices[:4]).reshape(4), (tr.axis,))
        tr._build_step().lower(*sds_like(args)).compile()
        print("ZERO-STEP(dp=4, adam+clip) TPU AOT COMPILE: OK")
    finally:
        flagmod.set_flags({"dense_zero": "off"})


if __name__ == "__main__":
    main()
