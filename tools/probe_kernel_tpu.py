"""Compile + value + timing probe of the Pallas sorted-scatter kernel on
the real TPU (the bench preflight's big sibling). Run manually after any
kernel change:

    python tools/probe_kernel_tpu.py

Prints per-shape timing vs the XLA scatter path so kernel-vs-fallback
decisions (core/flags.py sparse_scatter_kernel) stay evidence-based.
"""
import time

import numpy as np

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.pallas_kernels.sorted_scatter import (
    sorted_scatter_accumulate)


def sync(x):
    return float(np.asarray(x).ravel()[0])


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)

    # Small correctness probe first (the preflight shape).
    out = np.asarray(sorted_scatter_accumulate(
        jnp.asarray(np.arange(64, dtype=np.int32)),
        jnp.ones((64, 8), jnp.float32), 9000))
    if not ((out[:64] == 1.0).all() and (out[64:] == 0.0).all()):
        raise RuntimeError("small value check FAILED")
    print("small value check: ok")

    # Bench-scale value check vs XLA scatter.
    n, rows_n, aw = 425_984, 4_194_304, 20
    rows = rng.integers(0, rows_n, n).astype(np.int32)
    payload = rng.standard_normal((n, aw)).astype(np.float32)
    rows_j = jnp.asarray(rows)
    pay_j = jnp.asarray(payload)

    acc = sorted_scatter_accumulate(rows_j, pay_j, rows_n)
    xla = jnp.zeros((rows_n, aw), jnp.float32).at[rows_j].add(pay_j)
    err = float(jnp.max(jnp.abs(acc - xla)))
    print(f"bench-scale max |kernel - xla| = {err:.3e}")
    if not err < 1e-3:
        raise RuntimeError(f"value mismatch at scale: {err}")

    f_kernel = jax.jit(lambda r, p: sorted_scatter_accumulate(r, p, rows_n))
    f_xla = jax.jit(
        lambda r, p: jnp.zeros((rows_n, aw), jnp.float32).at[r].add(p))
    for name, f in (("kernel", f_kernel), ("xla", f_xla)):
        sync(f(rows_j, pay_j))  # warm
        t0 = time.perf_counter()
        for _ in range(5):
            sync(f(rows_j, pay_j))
        dt = (time.perf_counter() - t0) / 5
        print(f"{name}: {dt * 1e3:.1f} ms per call")


if __name__ == "__main__":
    main()
