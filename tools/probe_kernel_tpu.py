"""Compile + value + timing probe of BOTH Pallas sorted-stream kernels
(push scatter + pull gather) on the real TPU (the bench preflight's big
sibling). Run manually after any kernel change:

    python tools/probe_kernel_tpu.py

Prints per-shape timing vs the XLA scatter/gather paths so
kernel-vs-fallback decisions (core/flags.py sparse_scatter_kernel /
sparse_gather_kernel) stay evidence-based.
"""
import time

import numpy as np

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.pallas_kernels.sorted_gather import sorted_gather
from paddlebox_tpu.ops.pallas_kernels.sorted_scatter import (
    sorted_scatter_accumulate)


def sync(x):
    return float(np.asarray(x).ravel()[0])


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)

    # Small correctness probe first (the preflight shape).
    out = np.asarray(sorted_scatter_accumulate(
        jnp.asarray(np.arange(64, dtype=np.int32)),
        jnp.ones((64, 8), jnp.float32), 9000))
    if not ((out[:64] == 1.0).all() and (out[64:] == 0.0).all()):
        raise RuntimeError("small value check FAILED")
    print("small value check: ok")

    # Bench-scale value check vs XLA scatter.
    n, rows_n, aw = 425_984, 4_194_304, 20
    rows = rng.integers(0, rows_n, n).astype(np.int32)
    payload = rng.standard_normal((n, aw)).astype(np.float32)
    rows_j = jnp.asarray(rows)
    pay_j = jnp.asarray(payload)

    acc = sorted_scatter_accumulate(rows_j, pay_j, rows_n)
    xla = jnp.zeros((rows_n, aw), jnp.float32).at[rows_j].add(pay_j)
    err = float(jnp.max(jnp.abs(acc - xla)))
    print(f"bench-scale max |kernel - xla| = {err:.3e}")
    if not err < 1e-3:
        raise RuntimeError(f"value mismatch at scale: {err}")

    f_kernel = jax.jit(lambda r, p: sorted_scatter_accumulate(r, p, rows_n))
    f_xla = jax.jit(
        lambda r, p: jnp.zeros((rows_n, aw), jnp.float32).at[r].add(p))
    for name, f in (("scatter kernel", f_kernel), ("scatter xla", f_xla)):
        sync(f(rows_j, pay_j))  # warm
        t0 = time.perf_counter()
        for _ in range(5):
            sync(f(rows_j, pay_j))
        dt = (time.perf_counter() - t0) / 5
        print(f"{name}: {dt * 1e3:.1f} ms per call")

    # Pull gather at both bench pull widths, incl. the production
    # rows_per_shard+1 tail (rows_n + 1 is NOT a multiple of the kernel
    # BLOCK — the padded last-block fetch must survive on hardware, not
    # just in the AOT compile).
    for pw in (16, 40):
        tbl_j = jnp.asarray(
            rng.standard_normal((rows_n + 1, pw)).astype(np.float32))
        got = sorted_gather(rows_j, tbl_j, width=pw)
        ref = tbl_j[rows_j, :pw]
        gerr = float(jnp.max(jnp.abs(got - ref)))
        print(f"gather width {pw}: max |kernel - xla| = {gerr:.3e}")
        if not gerr == 0.0:
            raise RuntimeError(f"gather value mismatch: {gerr}")
        g_kernel = jax.jit(lambda r, t: sorted_gather(r, t, width=pw))
        g_xla = jax.jit(lambda r, t: t[r, :pw])
        for name, f in ((f"gather kernel w={pw}", g_kernel),
                        (f"gather xla w={pw}", g_xla)):
            sync(f(rows_j, tbl_j))  # warm
            t0 = time.perf_counter()
            for _ in range(5):
                sync(f(rows_j, tbl_j))
            dt = (time.perf_counter() - t0) / 5
            print(f"{name}: {dt * 1e3:.1f} ms per call")


if __name__ == "__main__":
    main()
