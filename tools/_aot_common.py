"""Shared helpers for the aot_check_* tools.

Import AFTER the tool has pinned its platform env (each tool sets
JAX_PLATFORMS/XLA_FLAGS before importing jax; this module only assumes
jax is importable by then).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def sds(tree):
    """Pytree of arrays -> pytree of ShapeDtypeStructs (compile-only
    stand-ins; nothing touches a device)."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), jnp.asarray(x).dtype),
        tree)
