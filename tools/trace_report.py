"""Summarize a span trace (and/or metrics JSONL) into per-stage tables.

The PROFILE.md workflow in one command: point it at the artifacts a
telemetry-enabled run wrote (``FLAGS_trace_path`` / ``FLAGS_metrics_path``)
and get the same shape of table the profile rounds hand-build — per
span name: count, total ms, p50/p95/max, share of the traced wall —
plus the registry's counters/gauges and bucket-estimated histogram
percentiles from the newest metrics snapshot.

    python tools/trace_report.py /tmp/run.trace.json
    python tools/trace_report.py --metrics /tmp/run.metrics.jsonl
    python tools/trace_report.py trace.json --metrics m.jsonl --top 15
"""

import argparse
import json
import sys
from collections import defaultdict


def _pct(durs, q):
    """Exact percentile over the recorded durations (nearest-rank)."""
    if not durs:
        return 0.0
    s = sorted(durs)
    i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[i]


def report_trace(path: str, top: int) -> None:
    with open(path) as f:
        obj = json.load(f)
    events = [e for e in obj.get("traceEvents", obj
                                 if isinstance(obj, list) else [])
              if e.get("ph") == "X"]
    if not events:
        print(f"{path}: no complete ('X') span events")
        return
    wall_us = (max(e["ts"] + e.get("dur", 0.0) for e in events)
               - min(e["ts"] for e in events))
    by_name = defaultdict(list)
    for e in events:
        by_name[e["name"]].append(e.get("dur", 0.0) / 1e3)  # us -> ms
    print(f"\n== {path}: {len(events)} spans, "
          f"{len(by_name)} names, wall {wall_us / 1e3:.1f} ms ==")
    hdr = (f"{'span':<28} {'count':>6} {'total_ms':>10} {'p50_ms':>9} "
           f"{'p95_ms':>9} {'max_ms':>9} {'share':>7}")
    print(hdr)
    print("-" * len(hdr))
    rows = sorted(by_name.items(), key=lambda kv: -sum(kv[1]))
    for name, durs in rows[:top]:
        total = sum(durs)
        share = total / (wall_us / 1e3) if wall_us else 0.0
        print(f"{name:<28} {len(durs):>6} {total:>10.2f} "
              f"{_pct(durs, 0.50):>9.3f} {_pct(durs, 0.95):>9.3f} "
              f"{max(durs):>9.3f} {share:>6.1%}")
    if len(rows) > top:
        print(f"... {len(rows) - top} more span names (--top to widen)")


def _hist_pct(buckets, counts, q):
    """Bucket-estimated percentile: the upper bound of the bucket where
    the cumulative count crosses q (the +inf bucket reports the last
    finite bound tagged '>')."""
    total = sum(counts)
    if not total:
        return "-"
    need = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= need:
            if i < len(buckets):
                return f"{buckets[i]:g}"
            return f">{buckets[-1]:g}"
    return f">{buckets[-1]:g}"


def report_metrics(path: str) -> None:
    last = None
    n = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            last = json.loads(line)
            n += 1
    if last is None:
        print(f"{path}: empty")
        return
    print(f"\n== {path}: {n} snapshots, newest ts={last.get('ts')} "
          f"labels={last.get('labels')} ==")
    hists = last.get("histograms", {})
    if hists:
        hdr = (f"{'histogram':<28} {'count':>8} {'mean_ms':>9} "
               f"{'p50<=':>8} {'p95<=':>8} {'max':>9}")
        print(hdr)
        print("-" * len(hdr))
        for name, h in sorted(hists.items()):
            mean = h["sum"] / h["count"] if h["count"] else 0.0
            print(f"{name:<28} {h['count']:>8} {mean:>9.3f} "
                  f"{_hist_pct(h['buckets'], h['counts'], 0.5):>8} "
                  f"{_hist_pct(h['buckets'], h['counts'], 0.95):>8} "
                  f"{(h['max'] if h['max'] is not None else 0):>9.3f}")
    gauges = last.get("gauges", {})
    if gauges:
        print(f"\n{'gauge':<44} {'value':>14}")
        print("-" * 59)
        for name, v in sorted(gauges.items()):
            print(f"{name:<44} {v:>14.4f}")
    counters = last.get("counters", {})
    if counters:
        print(f"\n{'counter':<44} {'value':>14}")
        print("-" * 59)
        for name, v in sorted(counters.items()):
            print(f"{name:<44} {v:>14}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?", help="Chrome trace JSON "
                    "(FLAGS_trace_path output)")
    ap.add_argument("--metrics", help="metrics JSONL "
                    "(FLAGS_metrics_path output)")
    ap.add_argument("--top", type=int, default=20,
                    help="max span rows (default 20)")
    args = ap.parse_args(argv)
    if not args.trace and not args.metrics:
        ap.error("pass a trace file and/or --metrics")
    if args.trace:
        report_trace(args.trace, args.top)
    if args.metrics:
        report_metrics(args.metrics)
    return 0


if __name__ == "__main__":
    sys.exit(main())
