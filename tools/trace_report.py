"""Summarize a span trace (and/or metrics JSONL) into per-stage tables.

The PROFILE.md workflow in one command: point it at the artifacts a
telemetry-enabled run wrote (``FLAGS_trace_path`` / ``FLAGS_metrics_path``)
and get the same shape of table the profile rounds hand-build — per
span name: count, total ms, p50/p95/max, share of the traced wall —
plus the registry's counters/gauges and bucket-estimated histogram
percentiles from the newest metrics snapshot.

    python tools/trace_report.py /tmp/run.trace.json
    python tools/trace_report.py --metrics /tmp/run.metrics.jsonl
    python tools/trace_report.py trace.json --metrics m.jsonl --top 15

Cross-process merge (``--merge``): stitch N per-process trace files
(each exported by ``core/trace.py`` with its wall-clock anchor and
peer clock offsets in ``otherData``) into ONE Perfetto-loadable trace —
per-process tracks on a single wall-aligned timeline, plus flow arrows
binding each RPC client span to its server span (the ``span``/``parent``
ids the distributed trace context stamps on ``rpc/*`` spans):

    python tools/trace_report.py --merge /tmp/fleet.trace.json \
        router.trace.json replica0.trace.json shard0.trace.json
"""

import argparse
import json
import os
import sys
from collections import defaultdict


def _pct(durs, q):
    """Exact percentile over the recorded durations (nearest-rank)."""
    if not durs:
        return 0.0
    s = sorted(durs)
    i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[i]


def report_trace(path: str, top: int) -> None:
    with open(path) as f:
        obj = json.load(f)
    events = [e for e in obj.get("traceEvents", obj
                                 if isinstance(obj, list) else [])
              if e.get("ph") == "X"]
    if not events:
        print(f"{path}: no complete ('X') span events")
        return
    wall_us = (max(e["ts"] + e.get("dur", 0.0) for e in events)
               - min(e["ts"] for e in events))
    by_name = defaultdict(list)
    for e in events:
        by_name[e["name"]].append(e.get("dur", 0.0) / 1e3)  # us -> ms
    print(f"\n== {path}: {len(events)} spans, "
          f"{len(by_name)} names, wall {wall_us / 1e3:.1f} ms ==")
    hdr = (f"{'span':<28} {'count':>6} {'total_ms':>10} {'p50_ms':>9} "
           f"{'p95_ms':>9} {'max_ms':>9} {'share':>7}")
    print(hdr)
    print("-" * len(hdr))
    rows = sorted(by_name.items(), key=lambda kv: -sum(kv[1]))
    for name, durs in rows[:top]:
        total = sum(durs)
        share = total / (wall_us / 1e3) if wall_us else 0.0
        print(f"{name:<28} {len(durs):>6} {total:>10.2f} "
              f"{_pct(durs, 0.50):>9.3f} {_pct(durs, 0.95):>9.3f} "
              f"{max(durs):>9.3f} {share:>6.1%}")
    if len(rows) > top:
        print(f"... {len(rows) - top} more span names (--top to widen)")


def _hist_pct(buckets, counts, q):
    """Bucket-estimated percentile: the upper bound of the bucket where
    the cumulative count crosses q (the +inf bucket reports the last
    finite bound tagged '>')."""
    total = sum(counts)
    if not total:
        return "-"
    need = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= need:
            if i < len(buckets):
                return f"{buckets[i]:g}"
            return f">{buckets[-1]:g}"
    return f">{buckets[-1]:g}"


def _report_occupancy(gauges: dict) -> None:
    """Pipeline occupancy / bottleneck section from the pipeline/*
    gauges the pass report publishes (core/pipeline_stats.py): per-stage
    busy + blocked shares of the last pass window, the implied bounding
    stage (highest busy share), and the headline fractions."""
    stages = {}
    for name, v in gauges.items():
        if not name.startswith("pipeline/"):
            continue
        rest = name[len("pipeline/"):]
        for suffix in ("busy_ms", "busy_frac", "blocked_up_frac",
                       "blocked_down_frac"):
            if rest.endswith("_" + suffix):
                stages.setdefault(rest[:-len(suffix) - 1], {})[suffix] = v
    if not stages:
        return
    hdr = (f"\n{'pipeline stage':<16} {'busy_ms':>10} {'busy':>7} "
           f"{'blk_up':>7} {'blk_dn':>7}")
    print(hdr)
    print("-" * len(hdr))
    for name in sorted(stages, key=lambda n: -stages[n].get("busy_frac",
                                                            0.0)):
        s = stages[name]
        print(f"{name:<16} {s.get('busy_ms', 0.0):>10.2f} "
              f"{s.get('busy_frac', 0.0):>6.1%} "
              f"{s.get('blocked_up_frac', 0.0):>6.1%} "
              f"{s.get('blocked_down_frac', 0.0):>6.1%}")
    bounding = max(stages, key=lambda n: stages[n].get("busy_frac", 0.0))
    parts = [f"bottleneck: {bounding}"]
    def pct(v):
        return f"{v:.1%}" if isinstance(v, (int, float)) else "-"

    for kind in ("train", "eval"):
        idle = gauges.get(f"pass/{kind}_device_idle_frac")
        host = gauges.get(f"pass/{kind}_host_critical_share")
        if idle is not None or host is not None:
            parts.append(f"{kind}: device_idle={pct(idle)} "
                         f"host_critical={pct(host)}")
    print("  ".join(parts))


def _report_quality(gauges: dict, counters: dict) -> None:
    """Model quality & data health section (core/quality.py): the
    COPC/calibration headline, every quality alarm counter, and the
    per-slot health gauges (coverage / zero rate / churn / skew) — so
    a PROFILE round reads model health beside the stage tables."""
    qg = {k: v for k, v in gauges.items() if k.startswith("quality/")}
    qa = {k: v for k, v in counters.items()
          if k.startswith("quality/")}
    if not qg and not qa:
        return
    print("\nmodel quality & data health")
    print("-" * 27)
    head = []
    for name, label in (("quality/copc", "copc"),
                        ("quality/calibration_error", "cal_err"),
                        ("quality/key_churn", "churn"),
                        ("quality/skew_top_share", "top_share")):
        v = qg.get(name)
        if v is not None:
            head.append(f"{label}={v:.4f}")
    if head:
        print("  ".join(head))
    alarms = {k: v for k, v in qa.items()
              if k.startswith("quality/alarms/")}
    if alarms:
        print("alarms: " + "  ".join(
            f"{k[len('quality/alarms/'):]}={v}"
            for k, v in sorted(alarms.items())))
    slots = sorted({k.rsplit("/", 1)[1] for k in qg
                    if k.startswith("quality/slot_coverage/")})
    if slots:
        hdr = (f"{'slot':<14} {'coverage':>9} {'zero':>7} "
               f"{'churn':>7} {'top1%':>7} {'auc_drop':>9}")
        print(hdr)
        for s in slots:
            def g(prefix):
                v = qg.get(f"quality/{prefix}/{s}")
                return f"{v:.4f}" if isinstance(v, (int, float)) else "-"
            print(f"{s:<14} {g('slot_coverage'):>9} "
                  f"{g('slot_zero_frac'):>7} {g('slot_churn'):>7} "
                  f"{g('slot_top_share'):>7} {g('slot_auc_drop'):>9}")


def _report_quantiles(quantiles: dict) -> None:
    """Streaming-digest percentiles (core/quantiles.py): exact-count,
    rel-error-bounded p50/p90/p99/p999 — the dispatch-latency and
    serving-SLO view, plus queue depths."""
    if not quantiles:
        return
    hdr = (f"\n{'quantile digest':<32} {'count':>8} {'p50':>9} "
           f"{'p90':>9} {'p99':>9} {'p999':>9} {'max':>9}")
    print(hdr)
    print("-" * len(hdr))
    for name, d in sorted(quantiles.items()):
        def fmt(v):
            return f"{v:.3f}" if isinstance(v, (int, float)) else "-"
        print(f"{name:<32} {d.get('count', 0):>8} {fmt(d.get('p50')):>9} "
              f"{fmt(d.get('p90')):>9} {fmt(d.get('p99')):>9} "
              f"{fmt(d.get('p999')):>9} {fmt(d.get('max')):>9}")


def report_metrics(path: str) -> None:
    last = None
    n = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            last = json.loads(line)
            n += 1
    if last is None:
        print(f"{path}: empty")
        return
    print(f"\n== {path}: {n} snapshots, newest ts={last.get('ts')} "
          f"labels={last.get('labels')} ==")
    hists = last.get("histograms", {})
    if hists:
        hdr = (f"{'histogram':<28} {'count':>8} {'mean_ms':>9} "
               f"{'p50<=':>8} {'p95<=':>8} {'max':>9}")
        print(hdr)
        print("-" * len(hdr))
        for name, h in sorted(hists.items()):
            mean = h["sum"] / h["count"] if h["count"] else 0.0
            print(f"{name:<28} {h['count']:>8} {mean:>9.3f} "
                  f"{_hist_pct(h['buckets'], h['counts'], 0.5):>8} "
                  f"{_hist_pct(h['buckets'], h['counts'], 0.95):>8} "
                  f"{(h['max'] if h['max'] is not None else 0):>9.3f}")
    _report_quantiles(last.get("quantiles", {}))
    _report_occupancy(last.get("gauges", {}))
    _report_quality(last.get("gauges", {}), last.get("counters", {}))
    gauges = last.get("gauges", {})
    if gauges:
        print(f"\n{'gauge':<44} {'value':>14}")
        print("-" * 59)
        for name, v in sorted(gauges.items()):
            print(f"{name:<44} {v:>14.4f}")
    counters = last.get("counters", {})
    if counters:
        print(f"\n{'counter':<44} {'value':>14}")
        print("-" * 59)
        for name, v in sorted(counters.items()):
            print(f"{name:<44} {v:>14}")


def merge_traces(objs, names=None) -> dict:
    """Stitch per-process trace objects into ONE Chrome/Perfetto trace.

    - Every file's events shift onto a single wall-clock timeline via
      its ``otherData.wall_anchor_ns`` (unix ns at that ring's ts 0);
      the earliest anchor becomes global ts 0. Files without an anchor
      (legacy exports) keep their local timeline at offset 0.
    - Each file keeps its own process track (pids colliding across
      files — in-process drills exporting multiple rings — are
      remapped), named ``host:pid (filename)``.
    - Flow arrows: an event whose ``args.parent`` matches another
      event's ``args.span`` gets a Chrome flow ``s``→``f`` pair (the
      RPC client→server hop the distributed trace context stamps), so
      Perfetto draws the request's path across process tracks.
    """
    names = names or [f"trace{i}" for i in range(len(objs))]
    anchors = []
    for obj in objs:
        od = obj.get("otherData") or {}
        anchors.append(int(od.get("wall_anchor_ns") or 0))
    known = [a for a in anchors if a]
    t0 = min(known) if known else 0
    merged = []
    used_pids = set()
    span_index = {}   # span id -> (pid, tid, ts)
    file_meta = []
    for i, obj in enumerate(objs):
        od = obj.get("otherData") or {}
        shift_us = (anchors[i] - t0) / 1e3 if anchors[i] else 0.0
        events = obj.get("traceEvents", obj
                         if isinstance(obj, list) else [])
        orig_pids = {e.get("pid", 0) for e in events}
        pid_map = {}
        for p in sorted(orig_pids):
            np_ = p
            while np_ in used_pids:
                np_ = (np_ or 1) + 100000
            pid_map[p] = np_
            used_pids.add(np_)
        label = (f"{od.get('host', '?')}:{od.get('pid', '?')} "
                 f"({os.path.basename(str(names[i]))})")
        for p in sorted(set(pid_map.values())):
            merged.append({"name": "process_name", "ph": "M", "pid": p,
                           "args": {"name": label}})
        file_meta.append({"file": str(names[i]), "label": label,
                          "wall_anchor_ns": anchors[i],
                          "shift_us": round(shift_us, 3),
                          "peer_offsets_ms": od.get("peer_offsets_ms",
                                                    {})})
        for e in events:
            e = dict(e)
            e["pid"] = pid_map.get(e.get("pid", 0), e.get("pid", 0))
            if "ts" in e:
                e["ts"] = e["ts"] + shift_us
            merged.append(e)
            a = e.get("args") or {}
            if e.get("ph") == "X" and a.get("span"):
                span_index[str(a["span"])] = (e["pid"], e.get("tid", 0),
                                              e["ts"])
    flows = []
    for e in merged:
        a = e.get("args") or {}
        parent = a.get("parent")
        if e.get("ph") != "X" or not parent:
            continue
        src = span_index.get(str(parent))
        if src is None:
            continue
        fid = f"{a.get('trace', '')}:{parent}"
        flows.append({"name": "rpc", "cat": "rpc", "ph": "s",
                      "id": fid, "pid": src[0], "tid": src[1],
                      "ts": src[2]})
        flows.append({"name": "rpc", "cat": "rpc", "ph": "f", "bp": "e",
                      "id": fid, "pid": e["pid"],
                      "tid": e.get("tid", 0), "ts": e["ts"]})
    return {"traceEvents": merged + flows,
            "displayTimeUnit": "ms",
            "otherData": {"merged_from": file_meta,
                          "flow_arrows": len(flows) // 2}}


def merge_files(paths, out_path: str) -> dict:
    objs = []
    for p in paths:
        with open(p) as f:
            objs.append(json.load(f))
    merged = merge_traces(objs, names=list(paths))
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    meta = merged["otherData"]
    print(f"merged {len(paths)} trace file(s) -> {out_path} "
          f"({len(merged['traceEvents'])} events, "
          f"{meta['flow_arrows']} flow arrows)")
    return merged


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="*", help="Chrome trace JSON "
                    "(FLAGS_trace_path output); several with --merge")
    ap.add_argument("--metrics", help="metrics JSONL "
                    "(FLAGS_metrics_path output)")
    ap.add_argument("--top", type=int, default=20,
                    help="max span rows (default 20)")
    ap.add_argument("--merge", metavar="OUT",
                    help="stitch the given trace files into ONE "
                         "Perfetto trace at OUT (wall-aligned process "
                         "tracks + cross-process flow arrows)")
    args = ap.parse_args(argv)
    if args.merge:
        if not args.trace:
            ap.error("--merge needs at least one input trace file")
        merge_files(args.trace, args.merge)
        report_trace(args.merge, args.top)
        return 0
    if not args.trace and not args.metrics:
        ap.error("pass a trace file and/or --metrics")
    for t in args.trace:
        report_trace(t, args.top)
    if args.metrics:
        report_metrics(args.metrics)
    return 0


if __name__ == "__main__":
    sys.exit(main())
