"""Follow-up probes: scatter cost scaling + merge-as-dense-sweep feasibility
+ transfer bandwidths. See tools/profile_step.py; results in PROFILE.md."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_tiny = jax.jit(lambda x: lax.slice(x.ravel(), (0,), (1,)))


def sync(r):
    return np.asarray(_tiny(jax.tree_util.tree_leaves(r)[0]))


def timeit(fn, *args, n=10, warmup=2):
    for _ in range(warmup):
        r = fn(*args)
    sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn(*args)
    sync(r)
    return (time.perf_counter() - t0) / n


def main():
    N_ROWS = 4 * 1024 * 1024
    D = 16
    n = 425984
    rng = np.random.default_rng(0)
    rows = jnp.asarray(rng.integers(0, N_ROWS, n), jnp.int32)
    srows = jnp.sort(rows)
    emb = jnp.asarray(rng.normal(size=(N_ROWS, D)), jnp.float32)
    grads = jnp.asarray(rng.normal(size=(n, D)), jnp.float32)
    W = 40
    gradsW = jnp.asarray(rng.normal(size=(n, W)), jnp.float32)
    fused = jnp.asarray(rng.normal(size=(N_ROWS, W)), jnp.float32)
    sync(fused)

    # scatter width scaling: 1 wide scatter vs several narrow
    t = timeit(jax.jit(lambda e, r, g: e.at[r].add(g)), fused, rows, gradsW)
    print(f"scatter-add [{n}x{W}]           {t*1e3:8.2f} ms")
    scalar = jnp.asarray(rng.normal(size=(N_ROWS,)), jnp.float32)
    gs = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    t = timeit(jax.jit(lambda e, r, g: e.at[r].add(g)), scalar, rows, gs)
    print(f"scatter-add [{n}x1]             {t*1e3:8.2f} ms")

    # scatter into SMALL table (row count scaling)
    small = jnp.asarray(rng.normal(size=(n, D)), jnp.float32)
    rsmall = jnp.asarray(rng.integers(0, n, n), jnp.int32)
    t = timeit(jax.jit(lambda e, r, g: e.at[r].add(g)), small, rsmall, grads)
    print(f"scatter-add into [{n}] rows     {t*1e3:8.2f} ms")

    # scatter .set vs .add
    t = timeit(jax.jit(lambda e, r, g: e.at[r].set(g)), emb, srows, grads)
    print(f"scatter-SET sorted [{n}x{D}]    {t*1e3:8.2f} ms")

    # gather with many indices from SMALL source (the aligned-merge path)
    big_idx = jnp.asarray(rng.integers(0, n, N_ROWS), jnp.int32)
    src = jnp.asarray(rng.normal(size=(n, 20)), jnp.float32)  # 34MB
    t = timeit(jax.jit(lambda s, i: s[i]), src, big_idx)
    print(f"gather [{N_ROWS}] from [{n}x20] {t*1e3:8.2f} ms")

    # searchsorted: 4M queries into sorted 426K keys
    skeys = jnp.sort(jnp.asarray(
        rng.choice(np.arange(N_ROWS, dtype=np.int32), n, replace=False)))
    queries = jnp.arange(N_ROWS, dtype=jnp.int32)
    t = timeit(jax.jit(lambda k, q: jnp.searchsorted(k, q)), skeys, queries)
    print(f"searchsorted 4M into 426K       {t*1e3:8.2f} ms")

    # searchsorted small into big (bucketing by shard boundary alternative)
    t = timeit(jax.jit(lambda k, q: jnp.searchsorted(k, q)),
               jnp.sort(queries), skeys)
    print(f"searchsorted 426K into 4M       {t*1e3:8.2f} ms")

    # cumsum-based alternatives: segment boundaries via diff of sorted ids
    @jax.jit
    def seg_merge(sr, g):
        is_start = jnp.concatenate([jnp.ones((1,), bool), sr[1:] != sr[:-1]])
        seg = jnp.cumsum(is_start) - 1
        return jax.ops.segment_sum(g, seg, num_segments=n)
    t = timeit(seg_merge, srows, grads)
    print(f"merge segment_sum->[{n}]        {t*1e3:8.2f} ms")

    # full dense-sweep merge: searchsorted + small-gather + where
    @jax.jit
    def dense_merge(table, urow, uval):
        # urow: sorted unique update rows [m] (padded with N_ROWS)
        # uval: merged updates [m, D]
        pos = jnp.searchsorted(urow, jnp.arange(N_ROWS, dtype=jnp.int32))
        pos_c = jnp.minimum(pos, urow.shape[0] - 1)
        hit = urow[pos_c] == jnp.arange(N_ROWS, dtype=jnp.int32)
        upd = uval[pos_c]
        return table + jnp.where(hit[:, None], upd, 0.0)
    urow = srows
    t = timeit(dense_merge, emb, urow, grads)
    print(f"dense-sweep merge total         {t*1e3:8.2f} ms")

    # D2H / H2D bandwidths (finishing what profile_step.py crashed before)
    for arr in (emb, scalar):
        sync(arr)
        t0 = time.perf_counter()
        h = np.asarray(arr)
        dt = time.perf_counter() - t0
        print(f"D2H {h.nbytes/1e6:7.1f} MB            {dt*1e3:8.2f} ms "
              f"({h.nbytes/dt/1e9:.3f} GB/s)")
    h = np.asarray(emb)
    for _ in range(2):
        t0 = time.perf_counter()
        d = jax.device_put(h)
        sync(d)
        dt = time.perf_counter() - t0
        print(f"H2D {h.nbytes/1e6:7.1f} MB            {dt*1e3:8.2f} ms "
              f"({h.nbytes/dt/1e9:.3f} GB/s)")

    # D2H in parallel chunks (does the tunnel parallelize?)
    from concurrent.futures import ThreadPoolExecutor
    chunks = [emb[i * (N_ROWS // 8):(i + 1) * (N_ROWS // 8)]
              for i in range(8)]
    for c in chunks:
        sync(c)
    t0 = time.perf_counter()
    with ThreadPoolExecutor(8) as ex:
        res = list(ex.map(np.asarray, chunks))
    dt = time.perf_counter() - t0
    tot = sum(r.nbytes for r in res)
    print(f"D2H {tot/1e6:7.1f} MB x8 threads   {dt*1e3:8.2f} ms "
          f"({tot/dt/1e9:.3f} GB/s)")


if __name__ == "__main__":
    main()
