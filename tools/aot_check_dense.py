"""AOT-compile the dense benchmark train steps (resnet50 bf16, BERT-base)
for TPU — no TPU needed (compile-only PJRT topology).

These two bench harnesses had never run on hardware before round 3 (both
carried calling-convention bugs), so their TPU-compile surface — notably
the bf16 conv forward/transpose path resnet now uses — is exactly the
kind of thing that would otherwise only fail inside the recorded run:

    python tools/aot_check_dense.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import Mesh, NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402


from tools._aot_common import sds  # noqa: E402


def check_resnet(sh) -> None:
    """bench_resnet50's step shape: bf16 compute params (BN stats f32),
    f32 master merge — the conv dtype-symmetry fix under autodiff.
    Uses the SAME amp helpers bench_resnet50 imports, so this check
    cannot drift from the step it certifies."""
    from paddlebox_tpu.amp import (cast_compute_except_stats as
                                   cast_compute)
    from paddlebox_tpu.amp import merge_bn_stats as merge_bn
    from paddlebox_tpu.models.resnet import ResNet
    model = ResNet(depth=50, num_classes=1000)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.sgd(0.1, momentum=0.9)

    def loss_fn(p, x, y):
        logits, p_new = model.apply(cast_compute(p), x, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y).mean(), p_new

    def step(p, s, x, y):
        (loss, p_new), g = jax.value_and_grad(loss_fn, has_aux=True)(
            p, x, y)
        updates, s = opt.update(g, s, p)
        return merge_bn(optax.apply_updates(p, updates), p_new), s, loss

    opt_state = jax.eval_shape(opt.init, sds(params))
    x = jax.ShapeDtypeStruct((128, 224, 224, 3), jnp.bfloat16,
                             sharding=sh)
    y = jax.ShapeDtypeStruct((128,), jnp.int32, sharding=sh)
    jax.jit(step).lower(sds(params), opt_state, x, y).compile()
    print("AOT resnet50 bf16 train step: OK")


def check_bert(sh) -> None:
    from paddlebox_tpu.models.bert import (BertConfig, bert_mlm_loss,
                                           init_bert)
    cfg = BertConfig()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-4)

    def step(p, s, tokens, targets, mask):
        loss, g = jax.value_and_grad(
            lambda p: bert_mlm_loss(p, cfg, tokens, targets, mask))(p)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    opt_state = jax.eval_shape(opt.init, sds(params))
    tok = jax.ShapeDtypeStruct((8, 128), jnp.int32, sharding=sh)
    msk = jax.ShapeDtypeStruct((8, 128), jnp.float32, sharding=sh)
    jax.jit(step).lower(sds(params), opt_state, tok, tok, msk).compile()
    print("AOT bert-base train step: OK")


def main() -> None:
    try:
        topo = topologies.get_topology_desc("v5e:2x2x1", "tpu")
    except Exception as e:  # noqa: BLE001 - any init failure means no AOT
        # Sentinel for CI: environments without libtpu's AOT topology
        # (matched by tests/test_aot_step.py to SKIP, not fail).
        print(f"TPU-AOT-TOPOLOGY-UNAVAILABLE: {e!r}")
        return
    sh = NamedSharding(Mesh([topo.devices[0]], ("d",)), P())
    check_bert(sh)
    check_resnet(sh)
    print("DENSE BENCH TPU AOT COMPILE: OK")


if __name__ == "__main__":
    main()
