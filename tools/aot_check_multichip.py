"""AOT-compile MULTI-CHIP training steps for TPU — no TPU needed.

`dryrun_multichip` proves the sharded programs are semantically correct
on virtual CPU devices; this tool proves they also pass the real
XLA:TPU pipeline — ICI collective lowering (all_to_all, ppermute,
psum), 1F1B's scan-over-stages, ring attention, and the Pallas kernels
inside shard_map — against a 4-device v5e compile-only topology:

    python tools/aot_check_multichip.py

Covers: (1) GPT hybrid pp=2 x sp=2 with the 1F1B schedule and ring
attention; (2) the sparse CTR step over dp=4 (table sharded over dp,
bucket-by-shard all-to-all pull/push); (3) the device-resident store's
sharded gather/scatter/append programs (request/serve/reply
all_to_all).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.experimental import topologies  # noqa: E402

from paddlebox_tpu.parallel import HybridTopology, build_mesh  # noqa: E402


from tools._aot_common import sds  # noqa: E402


def check_gpt_hybrid(topo) -> None:
    from paddlebox_tpu.models.gpt import (GPTConfig, init_gpt,
                                          make_gpt_train_step)
    cfg = GPTConfig(vocab_size=1024, d_model=128, n_heads=4, n_layers=4,
                    d_ff=256, max_seq_len=128, attention="ring")
    params, specs = init_gpt(jax.random.PRNGKey(0), cfg, pp_stages=2)
    opt = optax.adam(1e-3)
    mesh = build_mesh(HybridTopology(dp=1, pp=2, sp=2, mp=1),
                      devices=list(topo.devices))
    step = make_gpt_train_step(cfg, mesh, specs, opt, num_microbatches=2,
                               schedule="1f1b")
    opt_state = jax.eval_shape(opt.init, sds(params))
    tokens = jax.ShapeDtypeStruct((4, 128), jnp.int32)
    step.lower(sds(params), opt_state, tokens, tokens).compile()
    print("AOT gpt hybrid (pp=2 sp=2, 1f1b, ring attention): OK")


def check_ctr_dp4(topo) -> None:
    from jax.sharding import Mesh

    from paddlebox_tpu.core import flags as flagmod
    from paddlebox_tpu.data.slots import (DataFeedConfig, SlotBatch,
                                          SlotConf)
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    n_slots, emb_dim, batch = 4, 8, 256
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(n_slots))
    feed = DataFeedConfig(slots=slots, batch_size=batch,
                          slot_capacity_slack=1.0)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(n_slots)),
                   emb_dim=emb_dim, hidden=(64,))
    mesh_cpu = build_mesh(HybridTopology(dp=4))
    tr = CTRTrainer(model, feed, TableConfig(dim=emb_dim),
                    mesh=mesh_cpu,
                    config=TrainerConfig(auc_num_buckets=1 << 12))
    tr.init(seed=0)
    rng = np.random.default_rng(0)
    keys = np.sort(rng.choice(np.arange(1, 100_000, dtype=np.uint64),
                              20_000, replace=False))
    tr.engine.feed_pass([keys for _ in tr.engine.groups])
    tables = tr.engine.begin_pass()
    ids = {f"s{i}": rng.choice(keys, batch).astype(np.uint64)
           for i in range(n_slots)}
    b = SlotBatch(
        labels=(rng.random((batch, 1)) < 0.2).astype(np.float32),
        valid=np.ones((batch,), bool), ids=ids,
        segments={n: np.arange(batch, dtype=np.int32) for n in ids},
        lengths={n: np.ones((batch,), np.int32) for n in ids},
        dense={})
    rows = tr._map_batch_rows(b)
    segs_j = {n: jnp.asarray(b.segments[n]) for n in ids}
    dense_j = jnp.zeros((batch, 0), jnp.float32)
    args = (tables, tr.params, tr.opt_state, tr.auc_state, rows, segs_j,
            jnp.asarray(b.labels), jnp.asarray(b.valid), dense_j,
            jnp.zeros((), jnp.int32))
    tr.mesh = Mesh(np.array(topo.devices).reshape(4), (tr.axis,))
    flagmod.set_flags({"sparse_scatter_kernel": "pallas",
                       "sparse_gather_kernel": "pallas"})
    step = tr._build_step()
    step.lower(*sds(args)).compile()
    print("AOT ctr dp=4 (sharded table all-to-all pull/push): OK")


def check_device_store_sharded(topo) -> None:
    """The HBM-resident store's cross-chip programs: request/serve/reply
    all_to_all gather, write-back scatter, and on-device row append."""
    from jax.sharding import Mesh

    from paddlebox_tpu.embedding.device_store import (
        _append_fn_sharded, _gather_fn_sharded, _scatter_fn_sharded)

    mesh = Mesh(np.array(topo.devices).reshape(4), ("dp",))
    s, cap_store, w, rps, cap = 4, 1 << 18, 23, 1 << 16, 1 << 14

    # Resident values are a parts TUPLE since the slot-column split
    # (1-tuple under the fused layout; (hot, slot) under split/host).
    v = (jax.ShapeDtypeStruct((s * (cap_store + 1), w), jnp.float32),)
    rq = jax.ShapeDtypeStruct((s, s * cap), jnp.int32)
    ii = jax.ShapeDtypeStruct((s, 1), jnp.int32)
    iv = jax.ShapeDtypeStruct((s, w), jnp.float32)
    _gather_fn_sharded(mesh, "dp", s, cap, (w,), rps, cap_store).lower(
        v, rq, rq, ii, iv).compile()
    b = jax.ShapeDtypeStruct(((rps + 1) * s, w), jnp.float32)
    _scatter_fn_sharded(mesh, "dp", s, cap, (w,)).lower(
        v, b, rq, rq).compile()
    keys = jax.ShapeDtypeStruct((s * (1 << 12),), jnp.uint32)
    tmpl = jax.ShapeDtypeStruct((s, w), jnp.float32)
    st = jax.ShapeDtypeStruct((s,), jnp.int32)
    _append_fn_sharded(mesh, "dp", (w,), 1 << 12, 16, 0, 0.01).lower(
        v, keys, tmpl, st, st).compile()
    # Split placement variant: same collectives, two-part writes.
    hot = 16 + 3
    v2 = (jax.ShapeDtypeStruct((s * (cap_store + 1), hot), jnp.float32),
          jax.ShapeDtypeStruct((s * (cap_store + 1), w - hot),
                               jnp.float32))
    _gather_fn_sharded(mesh, "dp", s, cap, (hot, w - hot), rps,
                       cap_store).lower(v2, rq, rq, ii, iv).compile()
    _scatter_fn_sharded(mesh, "dp", s, cap, (hot, w - hot)).lower(
        v2, b, rq, rq).compile()
    print("AOT device store sharded gather/scatter/append: OK")


def main() -> None:
    try:
        topo = topologies.get_topology_desc("v5e:2x2x1", "tpu")
    except Exception as e:  # noqa: BLE001 - any init failure means no AOT
        # Sentinel for CI: environments without libtpu's AOT topology
        # (matched by tests/test_aot_step.py to SKIP, not fail).
        print(f"TPU-AOT-TOPOLOGY-UNAVAILABLE: {e!r}")
        return
    check_gpt_hybrid(topo)
    check_ctr_dp4(topo)
    check_device_store_sharded(topo)
    print("MULTICHIP TPU AOT COMPILE: OK")


if __name__ == "__main__":
    main()
