"""fleet_top: live one-scrape telemetry view of the whole cluster.

`top` for the serving/shard fleet: every framed service answers a
``metrics_snapshot`` RPC (PredictServer replicas, ShardServer hosts,
the FleetRouter — each with its instance registry), and this tool
scrapes them ALL in one sweep (``core/telemetry_scrape.py``), folds
them through ``monitor.merge_snapshots``, and renders one table —
per-replica predict p99 / rps / SLO breaches, per-shard served volume
and worst/p99 replication journal lag, router hop decomposition
(route / wire / replica-server ms), and per-process rpc
reconnect/retry totals.

    # live view, replicas discovered through the router's topology RPC
    python tools/fleet_top.py --router 127.0.0.1:7100 \
        --shards 127.0.0.1:7200,127.0.0.1:7201

    # one scrape, machine-readable (the tier-1 smoke)
    python tools/fleet_top.py --targets rep0=127.0.0.1:7300 --once --json

    # record a JSONL timeline while watching
    python tools/fleet_top.py --router ... --record /tmp/fleet.jsonl

    # trend sparklines (metrics_history) + fleet-wide ALERTS pane
    python tools/fleet_top.py --router ... --history --alerts

No jax import — runs anywhere the cluster network is reachable.
"""

import argparse
import json
import sys
import time


def build_targets(args) -> dict:
    from paddlebox_tpu.core import telemetry_scrape as ts
    targets = {}
    if args.router:
        try:
            targets.update(ts.discover_router_targets(
                args.router, timeout=args.timeout))
        except (OSError, ConnectionError, RuntimeError) as e:
            targets["router"] = args.router
            print(f"fleet_top: router discovery failed: {e!r}",
                  file=sys.stderr)
    for i, ep in enumerate(e for e in (args.shards or "").split(",") if e):
        targets[f"shard{i}"] = ep
    for t in args.targets or ():
        if "=" not in t:
            raise SystemExit(f"--targets wants LABEL=ENDPOINT, got {t!r}")
        label, ep = t.split("=", 1)
        targets[label] = ep
    if not targets:
        raise SystemExit(
            "no targets: pass --router and/or --shards and/or --targets")
    return targets


_COLS = (("target", 16, "{}"), ("throughput_rps", 9, "{:.1f}"),
         ("predict_p99_ms", 9, "{:.2f}"), ("slo_violations", 5, "{}"),
         ("replica_lag_worst", 6, "{:.0f}"),
         ("replica_lag_p99", 7, "{:.0f}"), ("shard_rows", 10, "{:.0f}"),
         ("routed", 8, "{}"), ("hop_wire_p99_ms", 9, "{:.2f}"),
         ("rpc_reconnects", 6, "{}"), ("rpc_retries", 6, "{}"),
         # Model-quality pane (core/quality.py): served/trained COPC,
         # calibration error, and the target's quality alarms — model
         # health in the same scrape as fleet health.
         ("copc", 6, "{:.3f}"), ("calibration_error", 8, "{:.4f}"),
         ("quality_alarms", 7, "{}"))

_HEADS = {"target": "target", "throughput_rps": "rps",
          "predict_p99_ms": "p99_ms", "slo_violations": "slo",
          "replica_lag_worst": "lag_w", "replica_lag_p99": "lag_p99",
          "shard_rows": "rows", "routed": "routed",
          "hop_wire_p99_ms": "wire_p99", "rpc_reconnects": "reconn",
          "rpc_retries": "retry", "copc": "copc",
          "calibration_error": "cal_err", "quality_alarms": "q_alarm"}


_SPARK = "\u2581\u2582\u2583\u2584\u2585\u2586\u2587\u2588"


def sparkline(values, width: int = 32) -> str:
    """Unicode sparkline of the LAST ``width`` values, min-max scaled
    (a flat series renders as a flat low bar)."""
    vs = [float(v) for v in values if isinstance(v, (int, float))]
    if not vs:
        return ""
    vs = vs[-width:]
    lo, hi = min(vs), max(vs)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vs)
    return "".join(_SPARK[min(int((v - lo) / span * 8), 7)]
                   for v in vs)


def _trend_rows(hist: dict) -> list:
    """(label, last, sparkline) rows for the trend pane off the
    cluster-merged history: predict rps + window p99, replication lag,
    and COPC — the four signals an operator trends first."""
    from paddlebox_tpu.core import timeseries
    h = timeseries.MetricHistory.from_dict(hist)
    pts = h.points()
    rows = []
    rps = [p["counters"].get("serving/predict_rpcs", 0) for p in pts[1:]]
    if any(rps):
        rows.append(("rps", rps[-1] if rps else 0, sparkline(rps)))
    p99s = []
    from paddlebox_tpu.core.quantiles import LogQuantileDigest
    for p in pts:
        d = (p.get("quantiles") or {}).get("serving/predict_ms")
        if d:
            q = LogQuantileDigest.from_dict(d).quantiles().get("p99")
            p99s.append(q if isinstance(q, (int, float)) else None)
        else:
            p99s.append(None)
    if any(v is not None for v in p99s):
        last = [v for v in p99s if v is not None][-1]
        rows.append(("p99_ms", round(last, 2), sparkline(p99s)))
    for label, name in (("lag", "multihost/replica_lag_p99"),
                        ("copc", "quality/copc")):
        vals = [p["gauges"].get(name) for p in pts]
        vals = [v for v in vals if isinstance(v, (int, float))]
        if vals:
            rows.append((label, round(vals[-1], 3), sparkline(vals)))
    return rows


def render_trend(rec: dict) -> None:
    hist = rec.get("history")
    if not isinstance(hist, dict) or not hist.get("points"):
        print("TREND: no history yet (is FLAGS_history_interval_s set?)")
        return
    rows = _trend_rows(hist)
    if rows:
        print("TREND (cluster-merged metrics_history)")
        for label, last, spark in rows:
            print(f"  {label:>7} {last!s:>9} {spark}")


def render_alerts(rec: dict) -> None:
    alerts = rec.get("alerts") or ()
    shown = [a for a in alerts if a.get("state") in ("firing",
                                                     "pending")]
    if not shown:
        print("ALERTS: none firing")
        return
    print("ALERTS (fleet-wide)")
    for a in shown:
        vf = a.get("value_fast")
        vf = f"{vf:g}" if isinstance(vf, (int, float)) else "-"
        th = a.get("threshold")
        th = f"{th:g}" if isinstance(th, (int, float)) else "-"
        print(f"  {a['state'].upper():>8} [{a.get('severity', '?')}] "
              f"{a.get('target', '?')}: {a.get('name')} "
              f"({a.get('metric')} fast={vf} vs {th})")


def render(rec: dict, *, clear: bool) -> None:
    if clear:
        sys.stdout.write("\x1b[H\x1b[2J")
    c = rec["cluster"]
    head = (f"fleet_top  {time.strftime('%H:%M:%S', time.localtime(rec['ts']))}"
            f"  targets={c['scraped']}/{c['scraped'] + c['unreachable']}")
    for k, label in (("fleet_predict_p99_ms", "fleet p99"),
                     ("fleet_route_p99_ms", "route p99"),
                     ("replica_lag_worst", "worst lag"),
                     ("copc", "copc"),
                     ("quality_alarms", "q_alarms")):
        v = c.get(k)
        if v is not None:
            head += f"  {label}={v:g}"
    print(head)
    hdr = " ".join(f"{_HEADS[name]:>{w}}" for name, w, _ in _COLS)
    print(hdr)
    print("-" * len(hdr))
    for row in rec["summary"]:
        cells = []
        for name, w, fmt in _COLS:
            v = row.get(name)
            cells.append(f"{fmt.format(v) if v is not None else '-':>{w}}")
        print(" ".join(cells))
    for label, err in rec.get("errors", {}).items():
        print(f"{label:>16} UNREACHABLE {err}")
    if rec.get("_show_history"):
        render_trend(rec)
    if rec.get("_show_alerts"):
        render_alerts(rec)
    sys.stdout.flush()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--router", help="FleetRouter endpoint: scraped AND "
                    "used to discover replica targets (topology RPC)")
    ap.add_argument("--shards", help="comma-separated ShardServer "
                    "endpoints")
    ap.add_argument("--targets", action="append", metavar="LABEL=EP",
                    help="explicit extra target, repeatable")
    ap.add_argument("--once", action="store_true",
                    help="one scrape, then exit")
    ap.add_argument("--json", action="store_true",
                    help="print the scrape as JSON (summary + cluster + "
                         "merged) instead of the table")
    ap.add_argument("--record", metavar="PATH",
                    help="append each scrape's summary to this JSONL")
    ap.add_argument("--history", action="store_true",
                    help="also scrape metrics_history and render the "
                         "TREND pane (unicode sparklines for "
                         "rps/p99/lag/copc off the cluster-merged ring)")
    ap.add_argument("--alerts", action="store_true",
                    help="render the fleet-wide ALERTS pane "
                         "(FIRING/PENDING SLO rules from alerts_active)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between scrapes (default 2)")
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="per-target RPC timeout (default 10)")
    args = ap.parse_args(argv)

    from paddlebox_tpu.core import telemetry_scrape as ts
    first = True
    while True:
        targets = build_targets(args)
        rec = ts.scrape_cluster(targets, timeout=args.timeout,
                                with_history=args.history)
        rec["_show_history"] = args.history
        rec["_show_alerts"] = args.alerts
        if args.record:
            ts.record_jsonl(args.record, rec)
        if args.json:
            keys = ["ts", "targets", "summary", "cluster", "errors",
                    "merged", "alerts"]
            if args.history:
                keys.append("history")
            out = {k: rec.get(k) for k in keys}
            print(json.dumps(out, default=str))
        else:
            render(rec, clear=not first and not args.once)
        if args.once:
            return 0 if not rec["errors"] else 1
        first = False
        try:
            time.sleep(max(args.interval, 0.2))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
