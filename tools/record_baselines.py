"""Unattended bench recorder: wait for the TPU tunnel, run every bench
config, and persist the measured numbers.

The axon tunnel flaps (observed 2026-07-31: wedged socket mid-bench for
30+ min) — so baseline recording must be able to run unattended and
seize whatever up-window appears:

    nohup setsid python tools/record_baselines.py > /tmp/record.log 2>&1 &

Per config it runs ``python bench.py <name>`` in a subprocess with a
hard timeout (the in-bench watchdog usually fires first and emits a
parseable *_FAILED line; the timeout is the backstop), retries once on
failure, and then:

- appends the result to ``BENCH_LOCAL.json`` (one JSON object per line,
  with config, commit, and timestamp) — the raw record;
- fills ``BASELINE_MEASURED.json`` for metrics that have no prior-round
  baseline (bench.py folds these into SELF_BASELINE so later runs get a
  real vs_baseline ratio; existing prior-round values are never
  overridden);
- rewrites the generated section of BASELINE.md's measured table.

Flags: --configs a,b,c  --skip-wait  --timeout-s N (per config).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# bench.py config name -> its metric key in bench.py's SELF_BASELINE
CONFIGS = {
    "deepfm": "deepfm_e2e",
    "wide_deep": "wide_deep",
    "resnet50": "resnet50",
    "bert_dp": "bert_dp",
    "gpt": "gpt",
    "graph": "graph_walk",
    "serving": "serving",
}

BEGIN = "<!-- record_baselines:begin -->"
END = "<!-- record_baselines:end -->"

# Configs whose steps read the flash-attention tiles (tuned tiles are
# applied to exactly this set — one constant, no drift).
ATTENTION_CONFIGS = {"gpt", "bert_dp"}


def _last_json_line(stdout: str):
    """Last stdout line that parses to a JSON OBJECT, or None (shared by
    the bench-output and tuner-output parsers)."""
    for cand in reversed(stdout.strip().splitlines()):
        cand = cand.strip()
        if not (cand.startswith("{") and cand.endswith("}")):
            continue
        try:
            d = json.loads(cand)
        except ValueError:
            continue
        if isinstance(d, dict):
            return d
    return None


def tpu_alive(timeout: int = 120) -> bool:
    """True only when a real TPU backend answers — a silent CPU fallback
    must read as 'down' or the recorder would burn full-scale runs whose
    results bench.py then rejects as non-tpu."""
    probe = ("import jax; assert jax.default_backend() == 'tpu'; "
             "import jax.numpy as jnp; "
             "jnp.ones(4).sum().block_until_ready()")
    try:
        return subprocess.run(
            [sys.executable, "-c", probe], cwd=REPO, timeout=timeout,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
        ).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def tune_flash_blocks(timeout_s: int = 900) -> dict:
    """Run the flash tile sweep at the gpt bench shape on the live chip;
    return FLAGS_* env overrides for the winner ({} on any failure —
    tuning is an optimization, never a blocker)."""
    try:
        proc = subprocess.run(
            [sys.executable, "tools/tune_flash_blocks.py", "--shape",
             "gpt"], cwd=REPO, capture_output=True, text=True,
            timeout=timeout_s)
    except (subprocess.TimeoutExpired, OSError):
        return {}
    d = _last_json_line(proc.stdout)
    best = d.get("best") if d else None
    if not isinstance(best, dict) or "block_q" not in best \
            or "block_k" not in best:
        return {}  # schema drift or tuner failure: default tiles
    env = {"FLAGS_flash_block_q": str(best["block_q"]),
           "FLAGS_flash_block_k": str(best["block_k"])}
    try:
        append_log("tune_flash_blocks", d)
    except OSError:
        pass  # a logging failure must not discard the winner
    return env


def run_bench(name: str, timeout_s: int,
              extra_env: dict = None) -> dict:
    """Run one config; return the parsed final JSON line (always returns
    a dict — synthesized error records for timeouts/crashes)."""
    env = {k: v for k, v in os.environ.items() if k != "PBX_BENCH_SCALE"}
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, "bench.py", name], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"metric": f"{name}_FAILED", "value": 0.0,
                "error": f"recorder timeout after {timeout_s}s"}
    out = _last_json_line(proc.stdout)
    if out is None:
        return {"metric": f"{name}_FAILED", "value": 0.0,
                "error": f"no JSON output (rc={proc.returncode}); "
                         f"stderr tail: {proc.stderr[-300:]!r}"}
    if "error" not in out and out.get("platform") != "tpu":
        # Never clobber an existing error (the watchdog's stalled-phase
        # message is the diagnostic this recorder exists to capture).
        out["error"] = (f"ran on platform {out.get('platform')!r}, not "
                        f"tpu — not a recordable baseline")
    return out


def git_head() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True).stdout.strip()
    except OSError:
        return "unknown"


def append_log(name: str, out: dict) -> None:
    with open(os.path.join(REPO, "BENCH_LOCAL.json"), "a") as f:
        f.write(json.dumps({"config": name, "commit": git_head(),
                            "ts": time.strftime(
                                "%Y-%m-%d %H:%M UTC", time.gmtime()),
                            **out}) + "\n")


def record(results: dict) -> None:
    """Rewrite the aggregate state (BASELINE_MEASURED.json + the
    generated BASELINE.md table) from ALL results so far."""
    commit = git_head()
    ts = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())

    # Fill first-time baselines (never override an existing value).
    path = os.path.join(REPO, "BASELINE_MEASURED.json")
    try:
        with open(path) as f:
            measured = json.load(f)
    except (OSError, ValueError):
        measured = {}
    for name, out in results.items():
        key = CONFIGS[name]
        if "error" not in out and out.get("value") and key not in measured:
            measured[key] = out["value"]
    with open(path, "w") as f:
        json.dump(measured, f, indent=1)

    # Rewrite the generated rows of BASELINE.md between the markers.
    md = os.path.join(REPO, "BASELINE.md")
    try:
        text = open(md).read()
    except OSError:
        return
    if BEGIN not in text:
        text += (f"\n### Auto-recorded runs (tools/record_baselines.py)\n"
                 f"\n{BEGIN}\n{END}\n")
    rows = ["| Config | Metric | Value | Unit | Commit | When |",
            "|---|---|---|---|---|---|"]
    for name, out in results.items():
        if "error" in out:
            rows.append(f"| {name} | — | FAILED ({out['error'][:60]}) | — "
                        f"| {commit} | {ts} |")
        else:
            rows.append(f"| {name} | {out['metric']} | {out['value']} "
                        f"| {out.get('unit', '')} | {commit} | {ts} |")
    pre, rest = text.split(BEGIN, 1)
    _, post = rest.split(END, 1)
    with open(md, "w") as f:
        f.write(pre + BEGIN + "\n" + "\n".join(rows) + "\n" + END + post)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default=",".join(CONFIGS))
    ap.add_argument("--skip-wait", action="store_true")
    ap.add_argument("--timeout-s", type=int, default=3600)
    ap.add_argument("--wait-limit-s", type=int, default=8 * 3600)
    args = ap.parse_args()

    if not args.skip_wait:
        t0 = time.monotonic()
        while not tpu_alive():
            if time.monotonic() - t0 > args.wait_limit_s:
                print("gave up waiting for TPU", flush=True)
                return
            print(f"tpu down, waiting ({time.strftime('%H:%M:%S')})",
                  flush=True)
            time.sleep(240)
    print("tpu alive — recording", flush=True)

    # Tile tuning first: the gpt/bert configs read FLAGS_flash_block_*
    # — record them with the tuned tiles, and record WHICH tiles in the
    # raw log (tune_flash_blocks appends its own line). Skipped when no
    # selected config uses attention — the sweep must not burn a scarce
    # tunnel up-window for nothing.
    flash_env = {}
    if set(args.configs.split(",")) & ATTENTION_CONFIGS:
        flash_env = tune_flash_blocks()
        if flash_env:
            print(f"flash tiles tuned: {flash_env}", flush=True)

    # One GLOBAL deadline for all retry waits: a permanently dead tunnel
    # must not hold the recorder hostage per-config (a FAILED row beats
    # a hung recorder).
    deadline = time.monotonic() + args.wait_limit_s
    results = {}
    for name in args.configs.split(","):
        for attempt in (1, 2):
            print(f"[{name}] attempt {attempt}", flush=True)
            out = run_bench(
                name, args.timeout_s,
                extra_env=flash_env if name in ATTENTION_CONFIGS else None)
            print(f"[{name}] -> {json.dumps(out)[:300]}", flush=True)
            if "error" not in out or attempt == 2:
                break
            # Tunnel may have died mid-bench: give it until the global
            # deadline to come back before the one retry. A live tunnel
            # always gets its retry (transient failures late in a long
            # run must not be recorded FAILED unretried); only a tunnel
            # still dead past the deadline forfeits it.
            alive = tpu_alive()
            while not alive and time.monotonic() < deadline:
                print("tpu lost, waiting", flush=True)
                time.sleep(240)
                alive = tpu_alive()
            if not alive:
                break
        results[name] = out
        append_log(name, out)
        record(results)  # persist incrementally — flaps lose nothing
    print("done", flush=True)


if __name__ == "__main__":
    main()
