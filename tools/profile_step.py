"""Micro-profile of the CTR device step's components at bench shapes.

VERDICT r02 task 2 asked for a recorded profile of the jitted step naming
the dominant op. This measures each stage as its own jitted function at the
exact bench shapes (4M-key x 16-dim table, 16384-sample batch, 26 slots),
plus raw D2H/H2D bandwidth (the end_pass/feed_pass transfer path). Run on
the bench chip:

    python tools/profile_step.py

Results recorded in PROFILE.md.

A stall watchdog (PBX_PROFILE_WATCHDOG_S, default 600 s; 0 disables)
guards the axon-tunnel wedge mode the bench learned the hard way
(BENCH_r05): if no probe completes within the limit, it prints one JSON
line with faulthandler thread stacks + the trace ring tail and exits 3 —
a hung probe run is diagnosable post-mortem instead of silent.
"""
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddlebox_tpu.core import report as _report
from paddlebox_tpu.core import trace as _trace

_WD = {"t": time.monotonic(), "phase": "start"}


def _tick(phase: str) -> None:
    _WD["t"] = time.monotonic()
    _WD["phase"] = phase
    if _trace.GLOBAL.enabled:
        _trace.instant("profile/" + phase)


def _watchdog_loop(limit: float) -> None:
    while True:
        time.sleep(5)
        if time.monotonic() - _WD["t"] > limit:
            try:
                tail = _trace.stall_forensics()
            except Exception as e:  # noqa: BLE001 - keep the record
                tail = {"error": f"forensics unavailable: {e!r}"}
            print(json.dumps({
                "metric": "profile_step_FAILED",
                "error": (f"watchdog: no probe progress in phase "
                          f"{_WD['phase']!r} for {limit:.0f}s"),
                "tail": tail,
            }, default=str), flush=True)
            os._exit(3)


def _start_watchdog() -> None:
    limit = float(os.environ.get("PBX_PROFILE_WATCHDOG_S", "600"))
    if limit <= 0:
        return
    import threading
    threading.Thread(target=_watchdog_loop, args=(limit,),
                     daemon=True).start()


# Sync on a 4-byte slice of the result: forces completion of the dispatch
# chain without transferring the (possibly hundreds of MB) result over the
# axon tunnel (~15 MB/s), which would swamp the op being measured.
_tiny = jax.jit(lambda x: lax.slice(x.ravel(), (0,), (1,)))


def sync(r):
    leaf = jax.tree_util.tree_leaves(r)[0]
    out = np.asarray(_tiny(leaf))
    _WD["t"] = time.monotonic()  # every completed probe feeds the dog
    return out


def timeit(fn, *args, n=10, warmup=2):
    for _ in range(warmup):
        r = fn(*args)
    sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn(*args)
    sync(r)
    return (time.perf_counter() - t0) / n


def _ingest_probes():
    """Host-ingest stage probes (round 13): each row isolates ONE stage
    of the disk→chunk→store path — parse only (all three parser tiers),
    shm handoff only (frame write + zero-copy attach), store build only
    (incremental vs sorted-run vs the dict fallback baseline) — so a
    PROFILE.md cost model can attribute the ingest wall per stage."""
    from paddlebox_tpu.data.parser import parse_block_numpy, parse_lines
    from paddlebox_tpu.data.columnar import instances_to_chunk
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.data import shm_channel
    from paddlebox_tpu.native.parser_py import parse_chunk_native
    from paddlebox_tpu.native.store_py import bench_index_build

    _tick("ingest-parse")
    n_lines, n_slots, dense_dim = 100_000, 26, 13
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(n_slots))
    slots += (SlotConf("d", is_dense=True, dim=dense_dim),)
    cfg = DataFeedConfig(slots=slots, batch_size=1024,
                         slot_capacity_slack=1.0)
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 1 << 40, (n_lines, n_slots))
    parts = [(np.char.add(np.char.add(
        (ids[:, 0] % 2).astype("U1"), " s0:"), ids[:, 0].astype("U20")))]
    line = parts[0]
    for j in range(1, n_slots):
        line = np.char.add(line, f" s{j}:")
        line = np.char.add(line, ids[:, j].astype("U20"))
    line = np.char.add(line, " d:" + ",".join(["0.5"] * dense_dim))
    block = ("\n".join(line.tolist()) + "\n").encode()

    t0 = time.perf_counter()
    chunk = parse_chunk_native(block, cfg)
    dt = time.perf_counter() - t0
    if chunk is not None:
        print(f"ingest parse native [{n_lines}]   {dt*1e3:8.1f} ms "
              f"({n_lines/dt:,.0f} rows/s)")
    else:
        print("ingest parse native          unavailable (no native lib)")
    t0 = time.perf_counter()
    chunk_np = parse_block_numpy(block, cfg)
    dt = time.perf_counter() - t0
    print(f"ingest parse numpy-bulk      {dt*1e3:8.1f} ms "
          f"({n_lines/dt:,.0f} rows/s)")
    t0 = time.perf_counter()
    instances_to_chunk(parse_lines(block.decode().split("\n"), cfg), cfg)
    dt = time.perf_counter() - t0
    print(f"ingest parse per-line        {dt*1e3:8.1f} ms "
          f"({n_lines/dt:,.0f} rows/s)")

    _tick("ingest-shm")
    chunk = chunk if chunk is not None else chunk_np
    nbytes = chunk.nbytes
    name = shm_channel.seg_name(os.getpid(), shm_channel.next_load_id(),
                                0, 0)
    t0 = time.perf_counter()
    shm_channel.write_chunk(chunk, name)
    got, release = shm_channel.read_chunk(name)
    dt = time.perf_counter() - t0
    assert got.num_rows == chunk.num_rows
    release()
    print(f"ingest shm roundtrip {nbytes/1e6:6.1f} MB {dt*1e3:8.1f} ms "
          f"({nbytes/dt/1e9:.2f} GB/s write+attach)")

    _tick("ingest-build")
    for mode in ("upsert", "bulk", "dict"):
        r = bench_index_build(4_000_000, chunk=1_000_000, mode=mode)
        print(f"store build {mode:7s} [4M]     "
              f"{4e6/r*1e3:8.1f} ms ({r:,.0f} keys/s)")


def main():
    # Ring-only tracing (file export when FLAGS_trace_path is set) +
    # the stall watchdog — same forensics discipline as bench.py.
    _report.init_telemetry_from_flags()
    _trace.GLOBAL.enable()
    _start_watchdog()
    _ingest_probes()
    _tick("setup")
    N_ROWS = 4 * 1024 * 1024        # pass table rows (pow2 bucket)
    D = 16
    BATCH = 16384
    SLOTS = 26
    n = BATCH * SLOTS               # ids per step = 425984

    rng = np.random.default_rng(0)
    rows = jnp.asarray(rng.integers(0, N_ROWS, n), jnp.int32)
    emb = jnp.asarray(rng.normal(size=(N_ROWS, D)), jnp.float32)
    state = jnp.asarray(np.abs(rng.normal(size=(N_ROWS, D))), jnp.float32)
    grads = jnp.asarray(rng.normal(size=(n, D)), jnp.float32)
    payload = jnp.asarray(rng.normal(size=(n, D + 3)), jnp.float32)
    fused = jnp.asarray(rng.normal(size=(N_ROWS, 2 * D + 8)), jnp.float32)
    sync(fused)

    print(f"shapes: table [{N_ROWS},{D}] ids [{n}]")
    _tick("dispatch-rtt")

    # Dispatch-latency probe (empty-step RTT): one trivial jitted
    # program, dispatched AND synced per iteration — the pure host-side
    # enqueue + completion round-trip with ~zero device work. This is
    # the per-step overhead FLAGS_trainer_steps_per_dispatch amortizes
    # (K steps ride one dispatch, so the hot loop pays RTT/K); on the
    # axon tunnel it has been the step's hidden floor.
    tiny = jnp.zeros((8,), jnp.float32)
    empty = jax.jit(lambda x: x + 1.0)
    np.asarray(empty(tiny))  # compile + warm
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        np.asarray(empty(tiny))
    t = (time.perf_counter() - t0) / iters
    print(f"empty-step dispatch RTT      {t*1e3:8.2f} ms "
          f"(amortized by steps_per_dispatch)")

    _tick("sort-gather-scatter")
    t = timeit(jax.jit(lambda r: jnp.argsort(r)), rows)
    print(f"argsort[{n}]                 {t*1e3:8.2f} ms")

    t = timeit(jax.jit(lambda r: jnp.sort(r)), rows)
    print(f"sort[{n}]                    {t*1e3:8.2f} ms")

    t = timeit(jax.jit(lambda e, r: e[r]), emb, rows)
    print(f"gather [{n}x{D}]             {t*1e3:8.2f} ms")

    t = timeit(jax.jit(lambda f, r: f[r]), fused, rows)
    print(f"gather fused [{n}x{2*D+8}]   {t*1e3:8.2f} ms")

    # Pull-side sorted-stream kernel (CopyForPull role) vs the XLA
    # gather at both bench pull widths — includes the kernel's argsort,
    # which the real step AMORTIZES by sharing it with the push scatter
    # (compute_bucketing), so the steady-state cost is lower than this
    # standalone row by ~the argsort line above.
    _tick("sorted-gather")
    from paddlebox_tpu.ops.pallas_kernels.sorted_gather import sorted_gather
    for pw in (16, 40):
        tbl = jnp.asarray(rng.normal(size=(N_ROWS, pw)), jnp.float32)
        sync(tbl)
        t = timeit(jax.jit(lambda t_, r: t_[r, :pw]), tbl, rows)
        print(f"gather xla [{n}x{pw}]        {t*1e3:8.2f} ms")
        t = timeit(jax.jit(
            lambda r, t_: sorted_gather(r, t_, width=pw)), rows, tbl)
        print(f"sorted_gather [{n}x{pw}]     {t*1e3:8.2f} ms "
              f"(incl. its own argsort)")

    t = timeit(jax.jit(lambda e, r, g: e.at[r].add(g)), emb, rows, grads)
    print(f"scatter-add [{n}x{D}]        {t*1e3:8.2f} ms")

    sorted_rows = jnp.sort(rows)
    t = timeit(jax.jit(lambda e, r, g: e.at[r].add(g)),
               emb, sorted_rows, grads)
    print(f"scatter-add sorted ids       {t*1e3:8.2f} ms")

    t = timeit(jax.jit(
        lambda e, r, g: e.at[r].add(g, unique_indices=True)),
        emb, sorted_rows, grads)
    print(f"scatter-add sorted+unique    {t*1e3:8.2f} ms")

    donating = jax.jit(lambda e, r, g: e.at[r].add(g), donate_argnums=(0,))
    e2 = jnp.array(emb)
    t = timeit(donating, e2, rows, grads, n=1, warmup=0)
    print(f"scatter-add donated (1x)     {t*1e3:8.2f} ms")

    _tick("segment-sum")
    # segment_sum path (the merge): ids -> full table-sized accumulator
    t = timeit(jax.jit(lambda p, r: jax.ops.segment_sum(
        p, r, num_segments=N_ROWS)), payload, rows)
    print(f"segment_sum->table [{n}]     {t*1e3:8.2f} ms")

    # segment_sum into a small (batch-sized) accumulator after sort-rank
    t = timeit(jax.jit(lambda p, r: jax.ops.segment_sum(
        p, r % n, num_segments=n)), payload, rows)
    print(f"segment_sum->batch [{n}]     {t*1e3:8.2f} ms")

    # dense optimizer sweep over full table (adagrad-style)
    @jax.jit
    def dense_update(e, s, acc):
        g = acc[:, :D]
        s2 = s + g * g
        return e - 0.05 * g * lax.rsqrt(s2 + 1e-8), s2
    acc = jnp.zeros((N_ROWS, D), jnp.float32)
    t = timeit(dense_update, emb, state, acc)
    print(f"dense adagrad sweep [{N_ROWS}x{D}]  {t*1e3:8.2f} ms")

    # one-hot matmul alternative for the pull (gather as matmul)? At
    # 426K x 4M that is infeasible; skip.

    _tick("mlp")
    # the MLP fwd+bwd at bench size, f32 and bf16
    dims = [SLOTS * D + 13, 400, 400, 400, 1]
    for dt_ in (jnp.float32, jnp.bfloat16):
        ws = [jnp.asarray(rng.normal(size=(a, b)) * 0.05, dt_)
              for a, b in zip(dims[:-1], dims[1:])]
        x = jnp.asarray(rng.normal(size=(BATCH, dims[0])), dt_)
        y = jnp.asarray(rng.random(BATCH) < 0.3, jnp.float32)

        def loss_fn(ws, x, y):
            h = x
            for w in ws[:-1]:
                h = jax.nn.relu(h @ w)
            logit = (h @ ws[-1])[:, 0].astype(jnp.float32)
            p = jax.nn.sigmoid(logit)
            return -jnp.mean(y * jnp.log(p + 1e-7)
                             + (1 - y) * jnp.log(1 - p + 1e-7))
        t = timeit(jax.jit(jax.grad(loss_fn)), ws, x, y)
        print(f"MLP fwd+bwd {dt_.__name__} [{BATCH}]    {t*1e3:8.2f} ms")

    # AUC histogram accumulate
    probs = jnp.asarray(rng.random(BATCH), jnp.float32)
    labels = jnp.asarray(rng.random(BATCH) < 0.3, jnp.float32)
    NB = 1 << 16

    @jax.jit
    def auc_acc(hist, probs, labels):
        b = jnp.clip((probs * NB).astype(jnp.int32), 0, NB - 1)
        idx = b + (labels.astype(jnp.int32)) * NB
        return hist.at[idx].add(1.0)
    hist = jnp.zeros((2 * NB,), jnp.float32)
    t = timeit(auc_acc, hist, probs, labels)
    print(f"AUC hist scatter [{BATCH}]   {t*1e3:8.2f} ms")

    _tick("pass-boundary")
    # Fused end/begin boundary program (FLAGS_pass_boundary_fuse) at
    # bench pass shapes: 4M-row resident store, 20K-key next pass, half
    # the pass shared with the ending one. Three rows: the end_pass
    # scatter alone, the remainder merge-gather alone (the two-dispatch
    # boundary), and the fused single-dispatch program. On the tunnel
    # the fused win is dominated by the saved dispatch RTT (the
    # empty-step row above), not the device time.
    W = 2 * D + 8
    PASS = 20_000
    rps = 1 << (PASS - 1).bit_length()          # pow2 rows_per_shard
    scratch = N_ROWS                            # store scratch row
    store_vals = jnp.asarray(
        rng.normal(size=(N_ROWS + 1, W)), jnp.float32)
    prev_block = jnp.asarray(rng.normal(size=(rps + 1, W)), jnp.float32)
    next_block = jnp.zeros((rps + 1, W), jnp.float32)
    prev_idx_h = np.full((rps,), scratch, np.int32)
    prev_idx_h[:PASS] = rng.choice(N_ROWS, PASS, replace=False)
    prev_idx = jnp.asarray(prev_idx_h)
    m = PASS // 2                               # shared remainder
    m_cap = 1 << (m - 1).bit_length()
    idx_h = np.full((m_cap,), scratch, np.int32)
    idx_h[:m] = rng.choice(N_ROWS, m, replace=False)
    place_h = np.full((m_cap,), rps, np.int32)
    place_h[:m] = rng.choice(PASS, m, replace=False)
    nidx, nplace = jnp.asarray(idx_h), jnp.asarray(place_h)

    # Non-donating probe twins of device_store's boundary programs (the
    # real ones donate the store/block, which a repeat-timing loop
    # cannot feed; op structure is identical).
    scat = jax.jit(lambda v, b, i: v.at[i].set(b[:rps]))
    merge = jax.jit(lambda b, v, i, p: b.at[p].set(v[i]).at[rps].set(0.0))

    @jax.jit
    def fused(v, pb, pi, nb, ni, pl):
        v = v.at[pi].set(pb[:rps])
        out = nb.at[pl].set(v[ni])
        return v, out.at[rps].set(0.0)

    t = timeit(scat, store_vals, prev_block, prev_idx)
    print(f"boundary scatter [{PASS}x{W}]    {t*1e3:8.2f} ms")
    t = timeit(merge, next_block, store_vals, nidx, nplace)
    print(f"boundary merge [{m}x{W}]     {t*1e3:8.2f} ms")
    t = timeit(fused, store_vals, prev_block, prev_idx, next_block,
               nidx, nplace)
    print(f"boundary fused (1 dispatch)  {t*1e3:8.2f} ms "
          f"(vs scatter+merge = 2 dispatches)")

    _tick("quantized-psum")
    # int8 dense-grad codec probe (FLAGS_dense_allreduce_dtype): the
    # blocked quantize -> dequantize round-trip at fused dense-grad
    # size — the per-step device cost quantized_psum adds on TOP of
    # the DCN byte win (the collective itself needs a multi-device
    # mesh; bench multihost carries the byte accounting).
    from paddlebox_tpu.multihost.quant import (dequantize_blocked,
                                               quantize_blocked)
    GRAD = 1 << 20                             # ~1M-param dense block
    QB = 128
    g8 = jnp.asarray(rng.normal(size=(8, GRAD // 8)), jnp.float32)

    @jax.jit
    def qdq(x):
        q, s = quantize_blocked(x, QB)
        return dequantize_blocked(q, s, x.shape[1], QB)

    t = timeit(qdq, g8)
    print(f"int8 grad codec round-trip [{GRAD}] {t*1e3:8.2f} ms "
          f"(block {QB})")

    _tick("bandwidth")
    # D2H bandwidth at end_pass sizes (np.asarray = the write-back path)
    for arr in (emb, jnp.asarray(rng.normal(size=(N_ROWS,)), jnp.float32)):
        sync(arr)
        t0 = time.perf_counter()
        h = np.asarray(arr)
        dt = time.perf_counter() - t0
        print(f"D2H {h.nbytes/1e6:7.1f} MB          {dt*1e3:8.2f} ms "
              f"({h.nbytes/dt/1e9:.3f} GB/s)")

    # H2D bandwidth (feed_pass path): device_put + 4-byte readback
    h = np.asarray(emb)
    t0 = time.perf_counter()
    d = jax.device_put(h)
    sync(d)
    dt = time.perf_counter() - t0
    print(f"H2D {h.nbytes/1e6:7.1f} MB          {dt*1e3:8.2f} ms "
          f"({h.nbytes/dt/1e9:.3f} GB/s)")


if __name__ == "__main__":
    main()
