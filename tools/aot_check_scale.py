"""AOT-compile the training steps at SCALE topologies (64 and 256 chips)
— evidence for the 8→256-chip scaling metric (BASELINE.md metric 3)
without 256 real chips: the real XLA:TPU pipeline lowers the full
multislice CTR step (slice-hierarchical dense sync, intra-slice
all-to-all pull/push with the DCN accumulator psum) and the hybrid GPT
step at production-shaped meshes.

    python tools/aot_check_scale.py            # 64-chip checks
    python tools/aot_check_scale.py --chips 256

Role of the reference's multi-node scale validation (its README's
hundreds-of-nodes claim rides gather_multi_node_grad + two-level NCCL,
heter_comm.h:156-172) — here the compiler is the witness: if XLA can
schedule the collectives over the 16x16 v5e topology, the program runs
when the chips exist.

Scope note: the compile-only topology is a SINGLE physical slice, so
the "slice" mesh axis here is logical (a device reshape) and its
collectives lower to ICI — this validates the program structure and
collective schedule at 256-chip scale, not the DCN transport itself.
The DCN hop's semantics are pinned by tests/test_multislice.py parity;
on real multi-slice hardware build_mesh routes the slice axis over DCN
via create_hybrid_device_mesh.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import Mesh  # noqa: E402


from tools._aot_common import sds  # noqa: E402


def check_ctr_multislice(topo, n_slices: int, dp: int) -> None:
    """Full CTR train step on slice x dp chips: table sharded over dp
    (intra-slice), batch over slice x dp, hierarchical dense sync, DCN
    push psum. The step is compiled from ShapeDtypeStructs only — no
    arrays ever touch the (non-addressable) AOT topology devices; the
    trainer is built on a tiny CPU mesh and its replica geometry is then
    repointed at the scale mesh before ``_build_step``."""
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.embedding.table import PassTable
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    n = n_slices * dp
    n_slots, emb_dim = 4, 8
    batch = 8 * n
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(n_slots))
    feed = DataFeedConfig(slots=slots, batch_size=batch,
                          slot_capacity_slack=1.0)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(n_slots)),
                   emb_dim=emb_dim, hidden=(64,))
    mesh_cpu = build_mesh(HybridTopology(slice=2, dp=2))
    tr = CTRTrainer(model, feed, TableConfig(dim=emb_dim), mesh=mesh_cpu,
                    config=TrainerConfig(auc_num_buckets=1 << 12))
    # Repoint replica geometry at the scale topology BEFORE building the
    # step: ndev (replicas), per-slot capacities, and the mesh itself.
    tr.mesh = Mesh(np.array(topo.devices).reshape(n_slices, dp),
                   ("slice", "dp"))
    tr.ndev = n
    tr._slot_caps = {s.name: feed.sparse_capacity(s, num_shards=n)
                     for s in feed.sparse_slots}

    # Hand-built arg shapes (what _map_batch_rows/begin_pass would feed).
    from paddlebox_tpu.embedding.table import table_widths
    rps = 1 << 14                       # rows per table shard
    _, ke, kw = table_widths(TableConfig(dim=emb_dim))
    w = emb_dim + 3 + ke + kw
    tables = tuple(
        PassTable(vals=jax.ShapeDtypeStruct((dp * (rps + 1), w),
                                            jnp.float32),
                  rows_per_shard=rps, num_shards=dp, dim=emb_dim,
                  ke=ke, kw=kw)
        for _ in tr.engine.groups)
    total_cap = sum(tr._slot_caps.values())
    rows = tuple(jax.ShapeDtypeStruct((total_cap,), jnp.int32)
                 for _ in tr.engine.groups)
    segs = {s.name: jax.ShapeDtypeStruct((tr._slot_caps[s.name],),
                                         jnp.int32)
            for s in feed.sparse_slots}
    params = sds(model.init(jax.random.PRNGKey(0)))
    opt_state = jax.eval_shape(tr._optax.init, params)
    auc = sds(tr._auc_init())
    args = (tables, params, opt_state, auc, rows, segs,
            jax.ShapeDtypeStruct((batch, 1), jnp.float32),
            jax.ShapeDtypeStruct((batch,), jnp.bool_),
            jax.ShapeDtypeStruct((batch, 0), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32))
    t0 = time.time()
    step = tr._build_step()
    step.lower(*args).compile()
    print(f"AOT ctr multislice slice={n_slices} dp={dp} "
          f"({n} chips, batch {batch}): OK in {time.time()-t0:.0f}s")


def check_gpt_scale(topo, n_slices: int, dp: int, pp: int, sp: int,
                    mp: int, schedule: str = "1f1b",
                    num_chunks: int = 1) -> None:
    from paddlebox_tpu.models.gpt import (GPTConfig, init_gpt,
                                          make_gpt_train_step)
    from paddlebox_tpu.parallel.topology import AXIS_ORDER

    n = n_slices * dp * pp * sp * mp
    cfg = GPTConfig(vocab_size=2048, d_model=256, n_heads=8,
                    n_layers=2 * pp * max(num_chunks, 1), d_ff=512,
                    max_seq_len=256, attention="ring")
    params, specs = init_gpt(jax.random.PRNGKey(0), cfg, pp_stages=pp)
    shape = {"slice": n_slices, "dp": dp, "pp": pp, "sp": sp, "mp": mp}
    dims = [shape.get(a, 1) for a in AXIS_ORDER]
    mesh = Mesh(np.array(topo.devices).reshape(dims), tuple(AXIS_ORDER))
    opt = optax.adam(1e-3)
    step = make_gpt_train_step(cfg, mesh, specs, opt, num_microbatches=2,
                               schedule=schedule, num_chunks=num_chunks)
    opt_state = jax.eval_shape(opt.init, sds(params))
    tokens = jax.ShapeDtypeStruct((4 * n_slices * dp, 256), jnp.int32)
    t0 = time.time()
    step.lower(sds(params), opt_state, tokens, tokens).compile()
    print(f"AOT gpt hybrid slice={n_slices} dp={dp} pp={pp} sp={sp} "
          f"mp={mp} schedule={schedule} ({n} chips): OK in "
          f"{time.time()-t0:.0f}s")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chips", type=int, default=64, choices=(64, 256))
    args = ap.parse_args()
    name = {64: "v5e:8x8x1", 256: "v5e:16x16x1"}[args.chips]
    try:
        topo = topologies.get_topology_desc(name, "tpu")
    except Exception as e:  # noqa: BLE001 - any init failure means no AOT
        print(f"TPU-AOT-TOPOLOGY-UNAVAILABLE: {e!r}")
        return
    if args.chips == 64:
        check_ctr_multislice(topo, n_slices=4, dp=16)
        check_gpt_scale(topo, n_slices=2, dp=4, pp=2, sp=2, mp=2)
        check_gpt_scale(topo, n_slices=2, dp=4, pp=2, sp=2, mp=2,
                        schedule="interleaved_1f1b", num_chunks=2)
    else:
        check_ctr_multislice(topo, n_slices=4, dp=64)
        check_gpt_scale(topo, n_slices=4, dp=8, pp=2, sp=2, mp=2)
        check_gpt_scale(topo, n_slices=4, dp=8, pp=2, sp=2, mp=2,
                        schedule="interleaved_1f1b", num_chunks=2)
    print(f"SCALE TPU AOT COMPILE ({args.chips} chips): OK")


if __name__ == "__main__":
    main()
